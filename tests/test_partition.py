"""Rule-based GSPMD sharding (ISSUE 15, partition.py).

Four layers of gates:

1. resolution semantics — ordering, right-alignment, mesh adaptation,
   scalar replication, and the teaching errors (unmatched param, dead
   rule, over-rank spec);
2. equivalences — tp.state_shardings through the rules layer matches
   the historical channel_spec exactly; replicated rules reproduce the
   pre-rules layout;
3. golden param paths — every registered model's param key paths are
   frozen (count + digest; the LM's full list inline since LM_RULES
   regexes name those paths), so a rename cannot silently turn a rule
   dead: this is the CI half, the runtime half is the dead-rule
   teaching error;
4. the ROADMAP item 2 acceptance gate — an LM config whose params +
   optimizer state exceed one device's budget trains AND serves on a
   sharded mesh: per-device `peak_hbm_bytes` (observe/profile.py
   program accounting; XLA memory_analysis is per-device) strictly
   below the replicated figure, losses fp-close across layouts, serve
   tokens bit-identical, zero jit-cache growth across steps.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from idc_models_tpu import mesh as meshlib, partition, tp
from idc_models_tpu.models import registry
from idc_models_tpu.models.lm import attention_lm, next_token_loss
from idc_models_tpu.observe import profile as prof
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, rmsprop, shard_batch,
)
from idc_models_tpu.train.step import place_state

# -- 1. resolution semantics ------------------------------------------------


def _mesh22():
    return meshlib.make_mesh({meshlib.DATA_AXIS: 2,
                              meshlib.MODEL_AXIS: 2})


def test_first_match_wins_and_right_alignment():
    rules = partition.PartitionRules((
        (r"special/kernel$", P(None, meshlib.DATA_AXIS)),
        (r"kernel$", P(meshlib.MODEL_AXIS)),
        (r".*", P()),
    ))
    tree = {"special": {"kernel": np.zeros((8, 8))},
            "other": {"kernel": np.zeros((4, 8)), "bias": np.zeros((8,))}}
    specs = rules.specs(tree, mesh=_mesh22())
    assert specs["special"]["kernel"] == P(None, "data")
    # right-aligned: a rank-1 spec on a rank-2 leaf shards the LAST dim
    assert specs["other"]["kernel"] == P(None, "model")
    assert specs["other"]["bias"] == P()          # catch-all


def test_mesh_adaptation_drops_missing_and_nondividing_axes():
    rules = partition.PartitionRules((
        (r".*", P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)),))
    tree = {"a": np.zeros((4, 6)),     # 6 % 2 == 0 on both axes
            "b": np.zeros((4, 7)),     # 7 % 2 != 0 -> model dropped
            "c": np.zeros(())}         # scalar -> replicated
    specs = rules.specs(tree, mesh=_mesh22())
    assert specs["a"] == P("data", "model")
    assert specs["b"] == P("data")     # trailing None stripped
    assert specs["c"] == P()
    # a mesh without the axes degenerates to replicated everywhere
    client = meshlib.make_mesh({meshlib.CLIENT_AXIS: 4})
    specs = rules.specs(tree, mesh=client)
    assert all(s == P() for s in jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P)))


def test_unmatched_param_teaches():
    rules = partition.PartitionRules(((r"kernel$", P()),))
    with pytest.raises(partition.PartitionError,
                       match="no partition rule matches.*catch-all"):
        rules.specs({"bias": np.zeros((4,))})
    # scalars never need a rule: they replicate, matched or not — a
    # rule set without a catch-all must not trip over TrainState.step
    specs = rules.specs({"kernel": np.zeros((4, 4)),
                         "step": np.zeros(())})
    assert specs["step"] == P()


def test_dead_rule_teaches_and_check_dead_opt_out():
    rules = partition.PartitionRules((
        (r"ghost$", P(meshlib.DATA_AXIS)), (r".*", P())))
    tree = {"kernel": np.zeros((4, 4))}
    with pytest.raises(partition.PartitionError, match="dead partition"):
        rules.specs(tree)
    # deliberate partial trees opt out
    assert rules.specs(tree, check_dead=False)["kernel"] == P()


def test_over_rank_spec_teaches():
    rules = partition.PartitionRules((
        (r".*", P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)),))
    with pytest.raises(partition.PartitionError, match="right-align"):
        rules.specs({"bias": np.zeros((4,))}, mesh=_mesh22())


def test_constructor_validation_teaches():
    with pytest.raises(partition.PartitionError, match="at least one"):
        partition.PartitionRules(())
    with pytest.raises(partition.PartitionError, match="PartitionSpec"):
        partition.PartitionRules(((r".*", "data"),))
    with pytest.raises(partition.PartitionError, match="does not"):
        partition.PartitionRules(((r"[", P()),))
    with pytest.raises(partition.PartitionError, match="twice"):
        partition.PartitionRules(((r".*", P("data", "data")),))


def test_optimizer_state_shards_with_its_param():
    """The FSDP contract: the rmsprop `nu` tree mirrors the params, its
    key paths carry the param path as a suffix, and re.search matches
    both — one rule shards a param AND its moments."""
    model = attention_lm(16, 32, embed_dim=8, num_heads=2, mlp_dim=16,
                         num_blocks=1)
    opt = rmsprop(1e-3)
    v = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32), params=v.params,
                       model_state=v.state,
                       opt_state=opt.init(v.params))
    mesh = _mesh22()
    specs = registry.LM_RULES.specs(state, mesh=mesh)
    flat = {name: s for name, s in partition.tree_paths(specs)}
    wq = [k for k in flat if k.endswith("mha/wq")]
    assert len(wq) == 2, f"param + nu moment expected, got {wq}"
    assert len({str(flat[k]) for k in wq}) == 1, (
        "optimizer moment sharded differently from its param")
    assert flat["step"] == P()


def test_shard_and_gather_tree_roundtrip(devices):
    mesh = _mesh22()
    rules = partition.PartitionRules((
        (r"w$", P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)), (r".*", P())))
    tree = {"w": np.arange(32.0).reshape(4, 8), "b": np.ones((3,))}
    placed = partition.shard_tree(mesh, rules, tree)
    assert placed["w"].sharding.spec == P("data", "model")
    gathered = partition.gather_tree(mesh, placed)
    assert gathered["w"].sharding.spec == P()
    np.testing.assert_array_equal(np.asarray(gathered["w"]), tree["w"])


# -- 2. equivalences --------------------------------------------------------


def test_tp_state_shardings_match_channel_spec(devices):
    """tp.state_shardings now resolves through partition.py; it must
    reproduce the historical shape-based channel rule EXACTLY (specs,
    not just layouts) on a representative mixed tree."""
    mesh = tp.dp_tp_mesh(4)
    n_model = mesh.shape[meshlib.MODEL_AXIS]
    tree = {"conv": np.zeros((3, 3, 3, 32)), "dense": np.zeros((512, 8)),
            "head": np.zeros((512, 1)), "bias": np.zeros((32,)),
            "odd": np.zeros((7,)), "scalar": np.zeros(()),
            "moment": {"conv": np.zeros((3, 3, 3, 32))}}
    new = tp.state_shardings(mesh, tree)
    for (name, sh) in partition.tree_paths(new):
        leaf = tree
        for part in name.split("/"):
            leaf = leaf[part]
        assert sh.spec == tp.channel_spec(leaf, n_model), name


# -- 3. golden param paths (the CI half of the dead-rule defense) -----------

# model -> (leaf count, sha256 over the sorted "/"-joined path list).
# Regenerate with tools shown in the assertion message after a
# DELIBERATE rename — and update any partition rule (registry.py) that
# named the old path, which is exactly the review moment this gate
# exists to force.
GOLDEN_PARAM_PATHS = {
    "vgg16": (28, "8bdae838ef019c5ec9955d8ad4ee850f16533b182b7b4936"
                  "08ca2792dc192a5d"),
    "mobilenet_v2": (158, "c156469a357f372eb81cdc47dd8a0071d94b0fcf27"
                          "8c8ba68f35c7cda287ec5f"),
    "densenet201": (604, "30655eff0c45e93d976b2a0cce7d239280edc865b3f"
                         "cb4e674d7d66b338a8047"),
    "small_cnn": (6, "79c36dd7b46160b8c18fec78cca771fe9a351f475234556"
                     "22b81e929a7ff51d9"),
    "lm": (32, "3336b997678bdb55e08e728b979482e60612929785f3dea64d6e5"
               "e83a943da71"),
}

# the LM's paths inline too: LM_RULES regexes name these, so a diff
# here shows EXACTLY which rule a rename would orphan
GOLDEN_LM_PATHS = [
    "block0/fc1/bias", "block0/fc1/kernel", "block0/fc2/bias",
    "block0/fc2/kernel", "block0/ln1/bias", "block0/ln1/scale",
    "block0/ln2/bias", "block0/ln2/scale", "block0/mha/bo",
    "block0/mha/wk", "block0/mha/wo", "block0/mha/wq", "block0/mha/wv",
    "block1/fc1/bias", "block1/fc1/kernel", "block1/fc2/bias",
    "block1/fc2/kernel", "block1/ln1/bias", "block1/ln1/scale",
    "block1/ln2/bias", "block1/ln2/scale", "block1/mha/bo",
    "block1/mha/wk", "block1/mha/wo", "block1/mha/wq", "block1/mha/wv",
    "embed", "head/bias", "head/kernel", "ln_f/bias", "ln_f/scale",
    "pos",
]


def _param_paths(init):
    # eval_shape: structure without allocating a single weight — the
    # zoo's big backbones stay cheap to enumerate
    params = jax.eval_shape(lambda r: init(r).params, jax.random.key(0))
    return sorted(name for name, _ in partition.tree_paths(params))


def _builders():
    out = {name: spec.build(1, 3).init
           for name, spec in registry.REGISTRY.items()}
    out["lm"] = attention_lm(16, 32, embed_dim=8, num_heads=2,
                             mlp_dim=16, num_blocks=2).init
    return out


def test_golden_param_paths_frozen():
    builders = _builders()
    assert set(builders) == set(GOLDEN_PARAM_PATHS)
    for name, init in builders.items():
        paths = _param_paths(init)
        digest = hashlib.sha256("\n".join(paths).encode()).hexdigest()
        want_n, want_digest = GOLDEN_PARAM_PATHS[name]
        assert (len(paths), digest) == (want_n, want_digest), (
            f"{name} param key paths changed — a rename can silently "
            f"turn a partition rule (models/registry.py) into a dead "
            f"rule. If deliberate: update any rule naming the old "
            f"path, then refresh GOLDEN_PARAM_PATHS to "
            f"({len(paths)}, {digest!r}). Current paths:\n" +
            "\n".join(paths))


def test_golden_lm_paths_inline():
    assert _param_paths(_builders()["lm"]) == GOLDEN_LM_PATHS


def test_no_dead_rules_against_own_model():
    """Every registered rule set resolves against its own model's param
    tree with zero dead rules (specs() raises otherwise) and at least
    one actually-sharded leaf for the LM on a 2x2 mesh."""
    mesh = _mesh22()
    for name, init in _builders().items():
        rules = registry.get_partition_rules(name)
        params = jax.eval_shape(lambda r: init(r).params,
                                jax.random.key(0))
        specs = rules.specs(params, mesh=mesh)   # raises on dead rules
        if name == "lm":
            sharded = [s for s in jax.tree.leaves(
                specs, is_leaf=lambda x: isinstance(x, P)) if s != P()]
            assert sharded, "LM rules sharded nothing on a 2x2 mesh"


# -- 4. the ROADMAP item 2 acceptance gate ----------------------------------

_VOCAB, _T, _E, _MLP, _NB = 256, 32, 128, 512, 2


def _lm_state(mesh):
    model = attention_lm(_VOCAB, _T, embed_dim=_E, num_heads=4,
                         mlp_dim=_MLP, num_blocks=_NB, mesh=mesh)
    opt = rmsprop(1e-2)
    v = model.init(jax.random.key(0))
    return model, opt, TrainState(
        step=jnp.zeros((), jnp.int32), params=v.params,
        model_state=v.state, opt_state=opt.init(v.params))


def _train_steps(mesh, rules, steps=3):
    model, opt, state = _lm_state(mesh)
    sh = rules.shardings(mesh, state) if rules is not None else None
    step = jit_data_parallel(
        make_train_step(model, opt, next_token_loss), mesh,
        axis=meshlib.DATA_AXIS, state_shardings=sh)
    state = place_state(mesh, state, rules=rules)
    rng = np.random.default_rng(0)
    x = shard_batch(
        mesh,
        jnp.asarray((rng.integers(0, _VOCAB, (8, 1))
                     + np.arange(_T)) % _VOCAB, jnp.int32),
        axis=meshlib.DATA_AXIS)
    compiled = step.lower(state, x, x, jax.random.key(2)).compile()
    cost = prof.program_report(compiled, name="gate.train")
    key, losses = jax.random.key(1), []
    for _ in range(steps):
        key, sub = jax.random.split(key)
        state, m = compiled(state, x, x, sub)
        losses.append(float(m["loss"]))
    # zero jit growth: the jitted wrapper compiles once on first call
    # and repeated calls stay on that executable
    key, sub = jax.random.split(key)
    state, _ = step(state, x, x, sub)
    n0 = step._cache_size()
    key, sub = jax.random.split(key)
    state, _ = step(state, x, x, sub)
    assert step._cache_size() == n0 == 1
    return losses, cost.peak_hbm_bytes


def test_sharded_lm_trains_under_single_device_budget(devices):
    """THE capacity gate: an LM config whose params + optimizer state
    exceed one device's (notional) budget trains on FSDP and TP meshes
    with per-device peak HBM strictly below the replicated single-
    device figure — measured by XLA program accounting, not asserted —
    and fp-close losses."""
    rules = registry.get_partition_rules("lm")
    rep_losses, rep_hbm = _train_steps(
        meshlib.fsdp_tp_mesh(1, 1, 1), None)
    assert rep_hbm is not None, "backend reported no memory analysis"
    # the replicated figure DEFINES the single-device budget this
    # config exceeds; the sharded layouts must fit strictly under it
    budget = rep_hbm * 0.9
    for name, mesh in (("fsdp", meshlib.fsdp_tp_mesh(2, 1, 1)),
                       ("tp", meshlib.fsdp_tp_mesh(1, 2, 1))):
        losses, hbm = _train_steps(mesh, rules)
        assert hbm < budget < rep_hbm, (
            f"{name}: per-device peak {hbm / 2**20:.2f} MiB not under "
            f"the budget {budget / 2**20:.2f} MiB "
            f"(replicated {rep_hbm / 2**20:.2f} MiB)")
        # fp-close across layouts (documented tolerance: bf16-free
        # f32 math, GSPMD reduction-order drift only)
        np.testing.assert_allclose(losses, rep_losses, rtol=2e-3)


def test_sharded_lm_serves_token_identical_under_budget(devices):
    """The serve half of the gate: the SAME params decode token-
    IDENTICAL through a TP-sharded Generator (params over "model", KV
    on its seq ring — independent axes) with the decode program's
    per-device peak HBM below the replicated figure."""
    from idc_models_tpu.models.lm import Generator

    model = attention_lm(_VOCAB, _T, embed_dim=_E, num_heads=4,
                         mlp_dim=_MLP, num_blocks=_NB)
    params = jax.device_get(model.init(jax.random.key(0)).params)
    rules = registry.get_partition_rules("lm")
    prompt = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)

    def serve(mesh, rules):
        g = Generator(params, embed_dim=_E, num_heads=4, num_blocks=_NB,
                      t_max=_T, mesh=mesh, partition_rules=rules)
        toks = np.asarray(g(prompt, 10))
        costs = g.program_costs(batch=1, steps=8)
        return toks, costs

    t0, c0 = serve(meshlib.fsdp_tp_mesh(1, 1, 1), None)
    t1, c1 = serve(meshlib.fsdp_tp_mesh(1, 2, 1), rules)
    np.testing.assert_array_equal(t0, t1)        # bit-identical greedy
    for prog in ("lm.prefill", "lm.decode"):
        assert (c1[prog].peak_hbm_bytes
                < c0[prog].peak_hbm_bytes), prog
    # KV kept its ring layout while params sharded: independent axes
    g = Generator(params, embed_dim=_E, num_heads=4, num_blocks=_NB,
                  t_max=_T, mesh=meshlib.fsdp_tp_mesh(1, 2, 1),
                  partition_rules=rules)
    kc, _ = g.init_caches(1)[0]
    used = [a for e in kc.sharding.spec if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]
    assert meshlib.MODEL_AXIS not in used, (
        "KV cache sharded over the weight axis — the independent-axes "
        "contract broke")


def test_engine_serves_identical_with_tp_rules(devices):
    """The continuous-batching engine on a ("model", "seq") mesh with
    LM rules produces bit-identical token streams to the seq-only
    replicated engine."""
    from idc_models_tpu.serve import LMServer, Request

    model = attention_lm(64, _T, embed_dim=32, num_heads=2, mlp_dim=64,
                         num_blocks=2)
    params = jax.device_get(model.init(jax.random.key(0)).params)
    rules = registry.get_partition_rules("lm")

    def serve(mesh, rules):
        s = LMServer(params, embed_dim=32, num_heads=2, num_blocks=2,
                     t_max=_T, n_slots=2, window=4, mesh=mesh,
                     partition_rules=rules)
        s.submit(Request(id="a", prompt=(1, 2, 3), max_new_tokens=10))
        s.submit(Request(id="b", prompt=(4, 5), max_new_tokens=8))
        out = {}
        for _ in range(40):
            for r in s.step():
                out[r.id] = r.tokens
            if len(out) == 2:
                break
        s.close()
        return out

    assert serve(meshlib.seq_mesh(1), None) == serve(
        meshlib.fsdp_tp_mesh(1, 2, 1), rules)


def test_paged_engine_serves_identical_with_tp_rules(devices):
    """The PAGED twin under TP rules: pool pages + page tables keep
    their seq layout (the paged folds' tok_specs ride
    mesh.batch_axes), params shard over "model" — token streams bit-
    identical to the contiguous-mesh paged engine."""
    from idc_models_tpu.serve import LMServer, Request

    model = attention_lm(64, _T, embed_dim=32, num_heads=2, mlp_dim=64,
                         num_blocks=2)
    params = jax.device_get(model.init(jax.random.key(0)).params)
    rules = registry.get_partition_rules("lm")

    def serve(mesh, rules):
        s = LMServer(params, embed_dim=32, num_heads=2, num_blocks=2,
                     t_max=_T, n_slots=2, window=4, mesh=mesh,
                     partition_rules=rules, prefill_chunk=8,
                     kv_page_size=8, kv_pages=8)
        s.submit(Request(id="a", prompt=(1, 2, 3), max_new_tokens=10))
        s.submit(Request(id="b", prompt=(4, 5), max_new_tokens=8))
        out = {}
        for _ in range(60):
            for r in s.step():
                out[r.id] = r.tokens
            if len(out) == 2:
                break
        s.close()
        return out

    assert serve(meshlib.seq_mesh(1), None) == serve(
        meshlib.fsdp_tp_mesh(1, 2, 1), rules)


def test_engine_model_axis_without_rules_teaches(devices):
    from idc_models_tpu.serve.engine import SlotEngine

    model = attention_lm(64, _T, embed_dim=32, num_heads=2, mlp_dim=64,
                         num_blocks=2)
    params = model.init(jax.random.key(0)).params
    with pytest.raises(ValueError, match="partition_rules"):
        SlotEngine(params, embed_dim=32, num_heads=2, num_blocks=2,
                   t_max=_T, mesh=meshlib.fsdp_tp_mesh(1, 2, 1))


def test_fit_identical_with_replicated_rules(devices):
    """train/loop.fit routes placement through the rules layer when
    given one; replicated rules must be BIT-identical to the historical
    no-rules path (same placement, same executables' math)."""
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import create_train_state, fit
    from idc_models_tpu.train.losses import binary_cross_entropy

    mesh = meshlib.data_mesh(4)
    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.random((32, 10, 10, 3)).astype(np.float32),
                      rng.integers(0, 2, 32).astype(np.int32))

    def run(rules):
        model = small_cnn(10, 3, 1)
        opt = rmsprop(1e-3)
        state = create_train_state(model, opt, jax.random.key(0))
        state, hist = fit(model, opt, binary_cross_entropy, state, ds,
                          None, mesh, epochs=1, batch_size=8,
                          verbose=False, rules=rules)
        return jax.device_get(state.params), hist["loss"]

    p0, l0 = run(None)
    p1, l1 = run(registry.REPLICATED_RULES)
    assert l0 == l1
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 p0, p1)


def test_population_round_identical_with_rules(devices):
    """Federated: the streamed wave accumulators inherit the rules'
    shardings (replicated on a client mesh) — the round is bit-
    identical with and without the rules plumbing."""
    from idc_models_tpu.federated import initialize_server
    from idc_models_tpu.federated.population import (
        ClientPopulation, CohortSampler, make_population_round,
    )
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train.losses import binary_cross_entropy

    mesh = meshlib.client_mesh(4)
    model = small_cnn(10, 3, 1)
    pop = ClientPopulation(64, examples_per_client=8, image_size=10,
                           seed=0)
    opt = rmsprop(1e-3)

    def run(rules):
        sampler = CohortSampler(pop, cohort_size=8, seed=1)
        rnd = make_population_round(
            model, opt, binary_cross_entropy, mesh, pop, sampler,
            wave_size=4, rules=rules)
        server = initialize_server(model, jax.random.key(0))
        server, metrics = rnd(server, rng=jax.random.key(2),
                              round_idx=0)
        return (jax.device_get(server.params),
                float(metrics["loss"]))

    p0, l0 = run(None)
    p1, l1 = run(registry.REPLICATED_RULES)
    assert l0 == l1
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 p0, p1)


# -- docs completeness (gated like BENCHMARKS.md) ---------------------------


def test_sharding_doc_complete():
    """docs/SHARDING.md documents every LM rule pattern, the public
    surface, and the CLI flags — the same doc-completeness discipline
    as the bench-key gate on docs/BENCHMARKS.md."""
    from pathlib import Path

    doc = (Path(__file__).parent.parent / "docs"
           / "SHARDING.md").read_text()
    for pattern in registry.LM_RULES.patterns:
        assert f"`{pattern}`" in doc, (
            f"LM rule {pattern!r} undocumented in docs/SHARDING.md")
    for needle in ("PartitionRules", "shard_tree", "gather_tree",
                   "--fsdp", "--tp", "right-align", "dead rule",
                   "peak_hbm_bytes",
                   # ISSUE 17: the checkpoint/rollout section rides the
                   # same gate — the rules layer is its addressing scheme
                   "MANIFEST.json", "save_sharded", "restore_sharded",
                   "peak_host_bytes", "canary", "swap_params",
                   "swap_adapters", "--rollout", "--canary-fraction",
                   "--checkpoint-every", "--save-ckpt",
                   "--rollout-adapters"):
        assert needle in doc, (
            f"docs/SHARDING.md missing {needle!r}")


def test_bench_compare_refuses_cross_device_kind(tmp_path):
    """ISSUE-15 satellite: bench_compare refuses a cross-device_kind
    diff (the r06 cpu record vs the r01-r05 TPU trail) unless
    explicitly overridden — and then stamps the output."""
    import json as _json
    import sys as _sys
    from pathlib import Path as _Path

    _sys.path.insert(0, str(_Path(__file__).parent.parent))
    try:
        import bench
    finally:
        _sys.path.pop(0)

    old = {"metric": "x", "value": 100.0, "device_kind": "TPU v5 lite"}
    new = {"metric": "x", "value": 50.0, "device_kind": "cpu"}
    (tmp_path / "BENCH_r01.json").write_text(_json.dumps(old))
    (tmp_path / "BENCH_r02.json").write_text(_json.dumps(new))
    with pytest.raises(ValueError, match="device kinds"):
        bench.bench_compare(tmp_path)
    out = bench.bench_compare(tmp_path, allow_cross_device=True)
    assert out["cross_device"] == ["TPU v5 lite", "cpu"]
    assert "value" in out["regressions"]   # still computed, but stamped
    # same-kind records stay uncomplaining
    new["device_kind"] = old["device_kind"]
    (tmp_path / "BENCH_r02.json").write_text(_json.dumps(new))
    assert "cross_device" not in bench.bench_compare(tmp_path)
