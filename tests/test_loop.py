"""Two-phase training loop, evaluator, checkpointing, observability
(reference C7/C8/C17/C18 parity)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.models import small_cnn
from idc_models_tpu.observe import JsonlLogger, Timer, plot_history
from idc_models_tpu.train import (
    TrainState, TwoPhaseConfig, create_train_state, checkpoint_exists,
    evaluate, fit, load_or_train, restore_checkpoint, rmsprop,
    save_checkpoint, two_phase_fit,
)
from idc_models_tpu.train.losses import binary_cross_entropy


def _data(n=192, seed=0):
    imgs, labels = synthetic.make_idc_like(n, size=10, seed=seed)
    return ArrayDataset(imgs, labels)


def test_fit_history_and_loss(devices):
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    train_ds, val_ds = _data(160), _data(64, seed=1)
    state, hist = fit(model, opt, binary_cross_entropy, state, train_ds,
                      val_ds, mesh, epochs=3, batch_size=32, verbose=False)
    assert len(hist["loss"]) == 3
    assert len(hist["val_accuracy"]) == 3
    assert hist["loss"][-1] < hist["loss"][0]
    assert int(state.step) == 3 * (160 // 32)


def test_central_storage_equals_mirrored(devices):
    """D2 parity toggle: host-resident params per step must be numerically
    identical to the mirrored (replicated) mode."""
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    train_ds = _data(64)

    def run(central):
        opt = rmsprop(1e-3)
        state = create_train_state(model, opt, jax.random.key(0))
        state, hist = fit(model, opt, binary_cross_entropy, state, train_ds,
                          None, mesh, epochs=2, batch_size=32,
                          central_storage=central, verbose=False)
        return jax.device_get(state.params), hist["loss"]

    p_c, l_c = run(True)
    p_m, l_m = run(False)
    np.testing.assert_allclose(l_c, l_m, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_c), jax.tree.leaves(p_m)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_evaluate_exact_vs_steps(devices):
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    ds = _data(100)  # not a multiple of 8: exercises padding
    m = evaluate(model, state, ds, binary_cross_entropy, mesh,
                 batch_size=32, with_auroc=True)
    assert np.isfinite(m["loss"]) and 0 <= m["auroc"] <= 1
    # direct computation over all 100 examples must match exactly
    logits, _ = model.apply(state.params, state.model_state,
                            jnp.asarray(ds.images), train=False)
    np.testing.assert_allclose(
        m["loss"], float(binary_cross_entropy(logits,
                                              jnp.asarray(ds.labels))),
        rtol=1e-5)
    m_steps = evaluate(model, state, ds, binary_cross_entropy, mesh,
                       batch_size=32, steps=2)
    assert np.isfinite(m_steps["loss"])  # 64-example floor sample (Q3)


def test_two_phase_fit(devices, tmp_path):
    mesh = meshlib.data_mesh(8)
    train_ds, val_ds = _data(128), _data(64, seed=1)
    log_path = tmp_path / "run.jsonl"
    with JsonlLogger(log_path) as logger:
        result = two_phase_fit(
            "small_cnn", 1, train_ds, val_ds, mesh,
            TwoPhaseConfig(lr=1e-3, epochs=2, fine_tune_epochs=2,
                           batch_size=32, eval_steps=2),
            artifact_path=str(tmp_path), logger=logger)
    assert len(result.history["loss"]) == 2
    assert len(result.history_fine["loss"]) == 2
    assert result.pretrain_seconds > 0 and result.fine_tune_seconds > 0
    assert np.isfinite(result.baseline["loss"])
    # C18 artifact
    assert (tmp_path / "logs" / "plot_dev8.png").exists()
    # jsonl has epoch + timer records
    records = [json.loads(l) for l in open(log_path)]
    events = {r["event"] for r in records}
    assert {"epoch", "timer"} <= events


def test_fit_resume_matches_straight_through(devices, tmp_path):
    """Epoch-granular loop checkpointing (SURVEY.md §5 build target):
    interrupt after 2 of 3 epochs, resume from the checkpoint dir, and
    land on exactly the straight-through trajectory (state + history)."""
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    train_ds, val_ds = _data(96), _data(32, seed=1)

    def run(epochs, ckpt=None):
        opt = rmsprop(1e-3)
        state = create_train_state(model, opt, jax.random.key(0))
        return fit(model, opt, binary_cross_entropy, state, train_ds,
                   val_ds, mesh, epochs=epochs, batch_size=32, seed=3,
                   verbose=False, checkpoint_dir=ckpt)

    s_full, h_full = run(3)
    d = str(tmp_path / "ckpt")
    run(2, ckpt=d)                      # "interrupted" after epoch 2
    s_res, h_res = run(3, ckpt=d)       # restart: resumes at epoch 3
    np.testing.assert_allclose(h_res["loss"], h_full["loss"], rtol=1e-6)
    np.testing.assert_allclose(h_res["val_loss"], h_full["val_loss"],
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(s_res.params)),
                    jax.tree.leaves(jax.device_get(s_full.params))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    assert int(s_res.step) == int(s_full.step)
    # only the latest epoch-versioned state is kept
    import pathlib
    states = list(pathlib.Path(d).glob("state_e*"))
    assert [p.name for p in states] == ["state_e3"]
    # a checkpoint longer than the requested schedule is refused loudly
    import pytest
    with pytest.raises(ValueError, match="trained for 3 epochs"):
        run(2, ckpt=d)
    # a checkpoint from a different run (different seed -> different
    # fingerprint) is ignored with a warning, not silently restored
    def run_seed9(epochs, ckpt):
        opt = rmsprop(1e-3)
        state = create_train_state(model, opt, jax.random.key(0))
        return fit(model, opt, binary_cross_entropy, state, train_ds,
                   val_ds, mesh, epochs=epochs, batch_size=32, seed=9,
                   verbose=False, checkpoint_dir=ckpt)

    with pytest.warns(UserWarning, match="different run"):
        _, h9 = run_seed9(3, ckpt=d)
    assert len(h9["loss"]) == 3  # trained from scratch, not restored


def test_two_phase_resumable_cli_dirs(devices, tmp_path):
    """two_phase_fit(checkpoint_dir=...) writes per-phase checkpoints and
    a rerun restores instead of retraining (same end state)."""
    mesh = meshlib.data_mesh(8)
    train_ds, val_ds = _data(64), _data(32, seed=1)
    cfg = TwoPhaseConfig(lr=1e-3, epochs=1, fine_tune_epochs=1,
                         batch_size=32, eval_steps=1)
    d = str(tmp_path / "ck")
    r1 = two_phase_fit("small_cnn", 1, train_ds, val_ds, mesh, cfg,
                       checkpoint_dir=d)
    assert checkpoint_exists(tmp_path / "ck" / "phase1" / "state_e1")
    assert checkpoint_exists(tmp_path / "ck" / "phase2" / "state_e2")
    assert (tmp_path / "ck" / "phase1" / "meta.json").exists()
    r2 = two_phase_fit("small_cnn", 1, train_ds, val_ds, mesh, cfg,
                       checkpoint_dir=d)
    for a, b in zip(jax.tree.leaves(jax.device_get(r1.state.params)),
                    jax.tree.leaves(jax.device_get(r2.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_checkpoint_roundtrip(devices, tmp_path):
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    path = tmp_path / "ckpt"
    assert not checkpoint_exists(path)
    save_checkpoint(path, state)
    assert checkpoint_exists(path)
    target = create_train_state(model, opt, jax.random.key(9))
    restored = restore_checkpoint(path, target)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_or_train_gate(devices, tmp_path):
    """The C8 pretrainer gate: trains once, then restores (fixing Q5)."""
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    path = tmp_path / "pretrained"
    calls = []

    def train_fn():
        calls.append(1)
        return create_train_state(model, opt, jax.random.key(0))

    target = create_train_state(model, opt, jax.random.key(1))
    s1, was_restored = load_or_train(path, target, train_fn)
    assert not was_restored and len(calls) == 1
    s2, was_restored = load_or_train(path, target, train_fn)
    assert was_restored and len(calls) == 1
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_profile_trace_writes_tensorboard_artifact(devices, tmp_path):
    """§5 tracing: profile_trace must actually produce a TensorBoard-
    viewable trace directory around device work (and no-op on None)."""
    from idc_models_tpu.observe import profile_trace

    with profile_trace(None):
        pass  # unconditional call-site contract
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    ds = _data(64)
    logdir = tmp_path / "trace"
    with profile_trace(str(logdir)):
        fit(model, opt, binary_cross_entropy, state, ds, None, mesh,
            epochs=1, batch_size=32, verbose=False)
    traces = list(logdir.rglob("*.trace.json.gz")) + \
        list(logdir.rglob("*.xplane.pb"))
    assert traces, f"no trace artifacts under {logdir}"


def test_timer_prints_reference_format(capsys):
    with Timer("Pre-training for 10 epochs") as t:
        pass
    out = capsys.readouterr().out
    assert out.startswith("Pre-training for 10 epochs took ")
    assert out.rstrip().endswith("seconds")
    assert t.seconds is not None and t.seconds >= 0


def test_plot_history_no_fine(tmp_path):
    hist = {"accuracy": [0.5, 0.6], "val_accuracy": [0.4, 0.5],
            "loss": [0.7, 0.6], "val_loss": [0.8, 0.7]}
    out = plot_history(tmp_path, hist, None, 4)
    assert os.path.exists(out) and out.endswith("plot_dev4.png")


def test_checkpoint_save_is_atomic(devices, tmp_path):
    """Torn-checkpoint hardening: a completed save carries the
    completion marker; a partial left by a crash mid-save (no marker) is
    refused by checkpoint_exists/restore, and load_or_train retrains
    over it instead of restoring garbage."""
    import pytest

    from idc_models_tpu.train.checkpoint import _COMPLETE_MARKER

    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    path = tmp_path / "ckpt"
    save_checkpoint(path, state)
    assert (path / _COMPLETE_MARKER).exists()
    assert not path.with_name("ckpt.tmp").exists()   # renamed into place

    # overwrite is atomic too and leaves no .tmp/.old residue
    save_checkpoint(path, state)
    assert checkpoint_exists(path)
    assert not path.with_name("ckpt.tmp").exists()
    assert not path.with_name("ckpt.old").exists()

    # simulate the crash: strip the marker -> the gate refuses it
    (path / _COMPLETE_MARKER).unlink()
    assert not checkpoint_exists(path)
    target = create_train_state(model, opt, jax.random.key(9))
    with pytest.raises(ValueError, match="torn partial"):
        restore_checkpoint(path, target)
    calls = []

    def train_fn():
        calls.append(1)
        return state

    got, was_restored = load_or_train(path, target, train_fn)
    assert not was_restored and len(calls) == 1      # retrained
    assert checkpoint_exists(path)                   # and re-saved whole


def test_jsonl_logger_arrays_and_close(tmp_path):
    """The _jsonable hardening: small arrays inline via tolist (a dict
    holding a jnp metrics VECTOR must not raise mid-run), oversized
    arrays summarize instead of flooding the log, and close() flushes +
    fsyncs so the records survive the process."""
    path = tmp_path / "run.jsonl"
    logger = JsonlLogger(path)
    logger.log(event="step", vec=jnp.arange(3.0),
               nested={"m": np.ones((2, 2), np.float32)},
               big=np.zeros((64, 64), np.float32),
               scalar=jnp.float32(1.5))
    logger.close()
    logger.close()                                   # idempotent
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert recs[0]["vec"] == [0.0, 1.0, 2.0]
    assert recs[0]["nested"]["m"] == [[1.0, 1.0], [1.0, 1.0]]
    assert recs[0]["big"] == {"__array__": True, "shape": [64, 64],
                              "dtype": "float32"}
    assert recs[0]["scalar"] == 1.5


def test_fit_nonfinite_loss_fails_fast(devices, tmp_path):
    """A NaN training loss must abort the run IMMEDIATELY with an error
    naming the epoch/step — not silently poison every remaining epoch
    and the saved checkpoint."""
    import pytest

    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    ds = _data(64)
    poisoned = ArrayDataset(np.full_like(ds.images, np.nan), ds.labels)
    ckpt = tmp_path / "fit_ckpt"
    with pytest.raises(FloatingPointError, match=r"epoch 1"):
        fit(model, opt, binary_cross_entropy, state, poisoned, None, mesh,
            epochs=3, batch_size=32, verbose=False,
            checkpoint_dir=str(ckpt))
    # the poisoned epoch was never checkpointed: nothing to resume into
    assert not (ckpt / "meta.json").exists()


def test_checkpoint_corruption_detected(devices, tmp_path):
    """Bit-flip and truncation of a COMPLETED checkpoint: restore must
    raise cleanly (never hand back a garbage TrainState), and
    load_or_train must fall back to retraining."""
    import pytest

    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    target = create_train_state(model, opt, jax.random.key(9))

    def corrupt(path, mode):
        data_files = sorted(
            (p for p in path.rglob("*")
             if p.is_file() and not p.name.startswith("_IDC")),
            key=lambda p: p.stat().st_size, reverse=True)
        victim = data_files[0]
        raw = bytearray(victim.read_bytes())
        if mode == "bitflip":
            raw[len(raw) // 2] ^= 0xFF
            victim.write_bytes(bytes(raw))
        else:
            victim.write_bytes(bytes(raw[: len(raw) // 2]))

    for mode in ("bitflip", "truncate"):
        path = tmp_path / f"ckpt_{mode}"
        save_checkpoint(path, state)
        assert checkpoint_exists(path)
        corrupt(path, mode)
        with pytest.raises(ValueError):
            restore_checkpoint(path, target)

        calls = []

        def train_fn():
            calls.append(1)
            return state

        with pytest.warns(UserWarning, match="RETRAINING"):
            got, was_restored = load_or_train(path, target, train_fn)
        assert not was_restored and len(calls) == 1
        # the fallback re-saved a WHOLE checkpoint over the corpse
        restored = restore_checkpoint(path, target)
        for a, b in zip(jax.tree.leaves(restored),
                        jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
