"""ISSUE 12: the disaggregated multi-replica serving tier
(serve/cluster/) against its three hard contracts:

1. PLACEMENT — the router places on live, non-draining, non-shedding
   replicas by load, deterministically, and every request's output is
   bit-identical to a serial `Generator` run (each replica carries the
   engine's serial-parity contract; the router must not break it).
2. DISAGGREGATION — a dedicated prefill replica publishes the prompt's
   chunk-boundary KV snapshot into the cluster prefix registry and the
   decode replica ADOPTS it: zero prefill chunks run on the decode
   replica, output bit-identical to a single-replica run.
3. FAILOVER — a killed replica's journal WAL migrates its accepted-
   but-unfinished requests onto the survivors through the normal
   placement path, bit-identically, with each request's trace_id and
   relative deadline preserved across the crash boundary (one rid grep
   over the two replicas' journals reconstructs submit -> crash ->
   migrate -> finish under a single trace_id).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.observe import JsonlLogger
from idc_models_tpu.serve import (
    PrefixRegistry, Request, RetryPolicy, Router, build_replica,
)

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2
CHUNK = 8


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _model_kw():
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ)


def _replica(params, rid, *, device=None, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    return build_replica(params, replica_id=rid, device=device,
                         **_model_kw(), **kw)


def _serial_tokens(params, prompt, steps):
    gen = Generator(params, mesh=None, cache_dtype=jnp.float32,
                    **_model_kw())
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps)
    return toks.tolist()[0]


def _requests(n, seed=5, budget=None):
    rng = np.random.default_rng(seed)
    return [Request(id=f"r{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + 2 * i)),
                    max_new_tokens=budget or 4 + (i % 5) * 2)
            for i in range(n)]


# -- 1. placement + parity --------------------------------------------------


def test_router_places_balanced_and_bit_identical(devices, params):
    """Two replicas on their own device slices, six greedy requests:
    placement balances by load, every output matches the serial
    Generator bit for bit, and the rollup pools both replicas."""
    reps = [_replica(params, f"r{i}", device=devices[i])
            for i in range(2)]
    router = Router(reps)
    reqs = _requests(6)
    out = router.run([(0.0, r) for r in reqs])
    assert sorted(r.id for r in out) == sorted(r.id for r in reqs)
    for q in reqs:
        got = router.poll(q.id)
        assert got is not None and got.status == "ok"
        assert got.tokens == _serial_tokens(params, q.prompt,
                                            q.max_new_tokens), q.id
    s = router.summary()
    # least-loaded placement over an idle fleet alternates
    assert s["cluster_placements"] == {"r0": 3, "r1": 3}
    assert s["cluster_requests"] == 6
    assert s["cluster_tokens"] == sum(len(router.poll(q.id).tokens)
                                      for q in reqs)
    assert s["cluster_replicas_live"] == 2


def test_placement_prefers_the_less_loaded_replica(devices, params):
    """A replica with queued work loses placement to an idle one —
    the health/load signal actually routes."""
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps)
    # preload r0 through the router's own surface: 3 requests land
    # alternately, then check the next placement goes to the lighter
    for q in _requests(3, seed=1):
        assert router.submit(q)
    loads = {r.replica_id: r.load() for r in reps}
    probe = Request(id="probe", prompt=(1, 2, 3), max_new_tokens=2)
    assert router.submit(probe)
    lighter = min(loads, key=lambda k: (loads[k], k))
    assert router._owner["probe"].replica_id == lighter
    router.drain()
    assert router.poll("probe").status == "ok"


def test_unplaceable_states_and_cluster_shed(devices, params):
    """Draining and dead replicas take no placements; when every live
    replica sheds, the router records the honest cluster-wide shed
    Result instead of queueing into a brownout."""
    reps = [_replica(params, f"r{i}", brownout_queue_high=4)
            for i in range(2)]
    router = Router(reps)
    router.drain_replica("r0")
    assert not reps[0].placeable()
    q = _requests(1, seed=2)[0]
    assert router.submit(q)
    assert router._owner[q.id].replica_id == "r1"
    router.drain()
    # now force both into shed: r0 is draining (already at stage 3 via
    # its brownout), push r1 there too
    reps[1].server.brownout.force_stage(3, reason="test")
    shed = Request(id="shed-me", prompt=(1, 2), max_new_tokens=2)
    assert router.submit(shed) is False
    got = router.poll("shed-me")
    assert got is not None and got.status == "shed"
    # the router-level shed is visible in the rollup even though no
    # replica ever saw the request (review fix)
    assert router.summary()["cluster_shed"] >= 1


def test_router_rejects_misconfigured_prefill_replicas(devices, params):
    """Disaggregation misconfiguration fails at FLEET BUILD with a
    named error, not on the first caller's submit (review fix)."""
    dec = _replica(params, "dc0")
    no_chunk = _replica(params, "pf0", role="prefill")
    with pytest.raises(ValueError, match="without prefill_chunk"):
        Router([dec, no_chunk],
               prefix_registry=PrefixRegistry(CHUNK, 1 << 20))
    chunked = _replica(params, "pf1", role="prefill",
                       prefill_chunk=CHUNK, prefix_cache_mb=1.0)
    with pytest.raises(ValueError, match="needs a prefix_registry"):
        Router([dec, chunked])
    with pytest.raises(ValueError, match="!= registry chunk"):
        Router([dec, chunked],
               prefix_registry=PrefixRegistry(CHUNK * 2, 1 << 20))


# -- 2. prefill/decode disaggregation ---------------------------------------


def test_prefill_decode_handoff_bit_identical(devices, params):
    """The decode replica never prefills: the prefill replica runs the
    chunks and publishes the boundary snapshot, the decode replica's
    admission adopts the WHOLE chunk-aligned prompt from the registry
    (its own cache counts the adoption, its engine dispatches zero
    prefill chunks), and the output is bit-identical to a
    single-replica/serial run. A second identical prompt short-circuits
    the prefill replica entirely (registry already covers it)."""
    registry = PrefixRegistry(CHUNK, 64 * 1024 * 1024)
    pre = _replica(params, "pf0", role="prefill", prefill_chunk=CHUNK,
                   prefix_cache_mb=8.0, shared_prefix=registry)
    dec = _replica(params, "dc0", role="decode", prefill_chunk=CHUNK,
                   prefix_cache_mb=8.0, shared_prefix=registry)
    router = Router([pre, dec], prefix_registry=registry)
    rng = np.random.default_rng(3)
    prompt = tuple(int(x) for x in rng.integers(0, VOCAB, 2 * CHUNK))
    router.run([(0.0, Request(id="h0", prompt=prompt,
                              max_new_tokens=6))])
    got = router.poll("h0")
    assert got.status == "ok"
    assert got.tokens == _serial_tokens(params, prompt, 6)
    # the handoff really happened, and the decode replica served the
    # FULL prompt from the registry: its local cache adopted all 16
    # tokens, so its engine ran zero prefill-chunk dispatches
    assert router.handoffs[0] == {
        "rid": "h0", "replica": "pf0", "prefix_tokens": 2 * CHUNK,
        "cached": False}
    cache = dec.server.engine.prefix_cache
    assert cache.shared_hits == 1
    assert cache.shared_hit_tokens == len(prompt)
    assert registry.hits == 1
    # prefill-role replicas never take decode placements
    assert router.summary()["cluster_placements"]["pf0"] == 0
    # a second identical prompt: the registry already covers it — the
    # prefill replica is skipped (cached handoff) and parity holds
    router.run([(0.0, Request(id="h1", prompt=prompt,
                              max_new_tokens=6))])
    assert router.poll("h1").tokens == got.tokens
    assert router.handoffs[1]["cached"] is True


def test_shared_registry_reuses_across_decode_replicas(devices, params):
    """Cross-replica prefix reuse WITHOUT dedicated prefill replicas:
    the first decode replica to prefill a hot prompt publishes it, and
    the other replica adopts instead of re-prefilling — one physical
    prefill cluster-wide."""
    registry = PrefixRegistry(CHUNK, 64 * 1024 * 1024)
    reps = [_replica(params, f"r{i}", prefill_chunk=CHUNK,
                     prefix_cache_mb=8.0, shared_prefix=registry)
            for i in range(2)]
    router = Router(reps, prefix_registry=registry)
    rng = np.random.default_rng(4)
    hot = tuple(int(x) for x in rng.integers(0, VOCAB, 2 * CHUNK))
    # two requests with the same prompt land on DIFFERENT replicas
    # (least-loaded alternation) in one burst
    reqs = [Request(id=f"s{i}", prompt=hot, max_new_tokens=4)
            for i in range(2)]
    router.run([(0.0, r) for r in reqs])
    owners = {router.poll(r.id).status for r in reqs}
    assert owners == {"ok"}
    want = _serial_tokens(params, hot, 4)
    assert all(router.poll(r.id).tokens == want for r in reqs)
    # one replica prefilled + published; the other adopted
    shared = sum(r.server.engine.prefix_cache.shared_hits
                 for r in reps)
    assert registry.publishes >= 1
    assert shared >= 1


# -- 3. drain + failover ----------------------------------------------------


def test_kill_drill_migrates_journal_bit_identical(devices, params,
                                                   tmp_path):
    """The acceptance drill: two replicas with journal WALs, a burst
    of requests, one replica killed mid-flight. Every journaled
    request completes on the survivor with output bit-identical to an
    uncrashed serial run, and a single rid grep over BOTH journals
    reconstructs submit -> crash -> migrate -> finish under ONE
    trace_id with the original relative deadline preserved
    (ISSUE 12 satellite)."""
    reps = [_replica(params, f"r{i}", device=devices[i],
                     journal_path=str(tmp_path / f"j{i}.jsonl"))
            for i in range(2)]
    router = Router(reps, retry=RetryPolicy(max_retries=2))
    rng = np.random.default_rng(7)
    reqs = [Request(id=f"k{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + i)),
                    max_new_tokens=8, deadline_s=120.0)
            for i in range(8)]
    for q in reqs:
        assert router.submit(q)
    for _ in range(2):
        router.step()
    # kill whichever replica still owns work (placement alternated, so
    # both do — pick r0 deterministically)
    migrated = router.kill_replica("r0")
    assert migrated, "the kill must strand journaled work"
    assert reps[0].state == "dead"
    router.drain()
    for q in reqs:
        got = router.poll(q.id)
        assert got is not None and got.status == "ok", (q.id, got)
        assert got.tokens == _serial_tokens(params, q.prompt, 8), q.id
    s = router.summary()
    assert s["cluster_migrations"] == len(migrated)
    assert s["cluster_replicas_dead"] == 1
    # the satellite's grep: one rid, two journals, one trace_id
    submits: dict = {}
    finishes: dict = {}
    for i in (0, 1):
        for line in (tmp_path / f"j{i}.jsonl").read_text().splitlines():
            rec = json.loads(line)
            if rec.get("event") == "journal_submit":
                submits.setdefault(rec["id"], []).append((i, rec))
            elif rec.get("event") == "journal_finish":
                finishes.setdefault(rec["id"], []).append((i, rec))
    for rid in migrated:
        subs = submits[rid]
        assert len(subs) == 2                  # dead replica + survivor
        assert {i for i, _ in subs} == {0, 1}
        tids = {rec["trace_id"] for _, rec in subs}
        assert len(tids) == 1, (rid, tids)     # ONE lifecycle identity
        # the ORIGINAL relative deadline rides the migration
        assert {rec["deadline_s"] for _, rec in subs} == {120.0}
        fins = finishes[rid]
        assert [i for i, _ in fins] == [1]     # finished on the survivor
        assert fins[0][1]["status"] == "ok"


def test_failover_keeps_trace_id_in_merged_timeline(devices, params,
                                                    tmp_path):
    """ISSUE 20 satellite: a journal-recovered request keeps its
    ORIGINAL trace_id across the crash boundary, and merging the
    router's and both replicas' jsonl files renders one timeline in
    which the failover re-placement (`cluster_migrate`, stamped with
    the dead source replica) is just another hop under that same
    trace_id."""
    from idc_models_tpu.observe.stats import (
        format_request_timeline, summarize_jsonl,
    )

    logs = [JsonlLogger(tmp_path / f"{n}.jsonl")
            for n in ("router", "r0", "r1")]
    reps = [_replica(params, f"r{i}", device=devices[i],
                     logger=logs[1 + i],
                     journal_path=str(tmp_path / f"j{i}.jsonl"))
            for i in range(2)]
    router = Router(reps, logger=logs[0])
    reqs = _requests(6, seed=9, budget=8)
    for q in reqs:
        assert router.submit(q)
    for _ in range(2):
        router.step()
    migrated = router.kill_replica("r0")
    assert migrated, "the kill must strand journaled work"
    router.drain()
    rid = migrated[0]
    got = router.poll(rid)
    assert got is not None and got.status == "ok"
    assert got.trace_id
    for lg in logs:
        lg.close()
    merged = summarize_jsonl([lg.path for lg in logs])
    tl = merged["requests"][rid]
    whats = [e["what"] for e in tl]
    # place on the victim ... failover hop ... finish on the survivor
    assert whats.index("cluster_place") < whats.index("cluster_migrate")
    assert whats.index("cluster_migrate") < whats.index("serve_finish")
    mig = next(e for e in tl if e["what"] == "cluster_migrate")
    assert mig["detail"]["src"] == "r0"
    assert mig["detail"]["replica"] == "r1"
    # ONE lifecycle identity: every router hop and the Result agree
    tids = {e["detail"]["trace_id"] for e in tl
            if e["what"].startswith("cluster_")}
    assert tids == {got.trace_id}
    assert "cluster_migrate" in format_request_timeline(merged, rid)
    # the frozen failover-hop schemas
    recs = [json.loads(line) for lg in logs
            for line in lg.path.read_text().splitlines()]
    assert {frozenset(r) for r in recs
            if r.get("event") == "cluster_migrate"} == {frozenset(
                {"ts", "event", "id", "replica", "src", "trace_id",
                 "hop"})}
    assert {frozenset(r) for r in recs
            if r.get("event") == "cluster_replica_dead"} == {frozenset(
                {"ts", "event", "replica", "error"})}


def test_drain_completes_in_flight_work(devices, params):
    """Draining a replica finishes what it holds (no migration, no
    loss) while new work routes around it."""
    reps = [_replica(params, f"r{i}", brownout_queue_high=8)
            for i in range(2)]
    router = Router(reps)
    reqs = _requests(4, seed=9)
    for q in reqs:
        assert router.submit(q)
    owned_by_r0 = [rid for rid, rep in router._owner.items()
                   if rep.replica_id == "r0"]
    assert owned_by_r0
    router.drain_replica("r0", wait=True)
    assert reps[0].idle()
    # drained replica finished its own work...
    for rid in owned_by_r0:
        assert router.poll(rid) is not None
    # ...its brownout sits at the shed stage, and new work avoids it
    assert reps[0].server.brownout.stage == 3
    late = Request(id="late", prompt=(1, 2, 3), max_new_tokens=2)
    assert router.submit(late)
    assert router._owner["late"].replica_id == "r1"
    router.drain()
    assert all(router.poll(q.id).status == "ok" for q in reqs)


def test_replica_step_failure_fails_over(devices, params, tmp_path):
    """An engine failure DURING a step (injected crash fault) is a
    replica death, not a cluster death: the router marks it dead and
    migrates its journal onto the survivor, bit-identically."""
    from idc_models_tpu.serve import ServeFault, ServeFaultPlan

    plan = ServeFaultPlan([ServeFault(kind="crash", tick=2)])
    crasher = build_replica(
        params, replica_id="r0", n_slots=2, window=4,
        cache_dtype=jnp.float32, journal_path=str(tmp_path / "j0.jsonl"),
        fault_plan=plan, **_model_kw())
    healthy = _replica(params, "r1",
                       journal_path=str(tmp_path / "j1.jsonl"))
    router = Router([crasher, healthy])
    reqs = _requests(4, seed=11, budget=8)
    for q in reqs:
        assert router.submit(q)
    router.drain()
    assert crasher.state == "dead"
    assert router.summary()["cluster_replicas_dead"] == 1
    assert router.summary()["cluster_migrations"] >= 1
    for q in reqs:
        got = router.poll(q.id)
        assert got is not None and got.status == "ok", (q.id, got)
        assert got.tokens == _serial_tokens(params, q.prompt, 8), q.id


def test_handoff_caller_error_does_not_kill_prefill_replica(devices,
                                                            params):
    """A prompt too long to ever admit is a CALLER error: the normal
    submission path raises the honest ValueError, and the prefill
    replica must survive it (review fix: the handoff wrapper used to
    read it as a replica fault and kill fleet infrastructure)."""
    registry = PrefixRegistry(CHUNK, 1 << 20)
    pre = _replica(params, "pf0", role="prefill", prefill_chunk=CHUNK,
                   prefix_cache_mb=2.0, shared_prefix=registry)
    dec = _replica(params, "dc0", prefill_chunk=CHUNK,
                   prefix_cache_mb=2.0, shared_prefix=registry)
    router = Router([pre, dec], prefix_registry=registry)
    too_long = Request(id="huge", prompt=tuple(range(SEQ)),
                       max_new_tokens=4)
    with pytest.raises(ValueError):
        router.submit(too_long)
    assert pre.state == "live"          # infrastructure unharmed
    ok = Request(id="fine", prompt=tuple(range(CHUNK)),
                 max_new_tokens=4)
    assert router.submit(ok)
    router.drain()
    assert router.poll("fine").status == "ok"


def test_no_decode_capable_replica_raises_not_spins(devices, params,
                                                    tmp_path):
    """With the last decode-capable replica dead, run()/drain() must
    raise the rebuild-the-fleet error instead of busy-looping (review
    fix: a surviving prefill replica used to defeat the all-dead
    guard)."""
    registry = PrefixRegistry(CHUNK, 1 << 20)
    dec = _replica(params, "dc0", prefill_chunk=CHUNK,
                   prefix_cache_mb=2.0, shared_prefix=registry,
                   journal_path=str(tmp_path / "j.jsonl"))
    pre = _replica(params, "pf0", role="prefill", prefill_chunk=CHUNK,
                   prefix_cache_mb=2.0, shared_prefix=registry)
    router = Router([dec, pre], prefix_registry=registry)
    assert router.submit(Request(id="a", prompt=(1, 2, 3),
                                 max_new_tokens=4))
    router.kill_replica("dc0")
    with pytest.raises(RuntimeError, match="rebuild the fleet"):
        router.drain()                  # migration backlog, no target
    with pytest.raises(RuntimeError, match="rebuild the fleet"):
        router.run([(0.0, Request(id="b", prompt=(1, 2),
                                  max_new_tokens=2))])


def test_hedge_first_result_wins_and_survives_owner_death(devices,
                                                          params,
                                                          tmp_path):
    """Straggler hedging: past hedge_after_s the request is duplicated
    onto the other replica; when the ORIGINAL owner then dies without
    a journal, the hedge copy answers under the original id (review
    fix: the loss path used to declare an error while the copy was
    still running) — and the result is the bit-identical stream."""
    t = [0.0]
    reps = [_replica(params, f"r{i}") for i in range(2)]
    log = JsonlLogger(tmp_path / "hedge.jsonl")
    router = Router(reps, hedge_after_s=0.5, clock=lambda: t[0],
                    logger=log)
    q = Request(id="h", prompt=(1, 2, 3), max_new_tokens=6)
    assert router.submit(q)
    owner = router._owner["h"]
    t[0] = 1.0                          # past the hedge threshold
    router.step()
    assert router.hedges_sent == 1
    router.kill_replica(owner.replica_id)
    out = router.drain()
    got = router.poll("h")
    assert got is not None and got.status == "ok"
    assert got.tokens == _serial_tokens(params, (1, 2, 3), 6)
    # exactly one Result surfaced for the rid — no spurious loss
    assert [r.id for r in out + router.results()].count("h") <= 2
    assert router.poll("h#h") is None   # the copy never leaks its id
    # the hedge hop joins the trace chain with a frozen schema
    log.close()
    hedges = [json.loads(line)
              for line in log.path.read_text().splitlines()
              if json.loads(line).get("event") == "cluster_hedge"]
    assert hedges and {frozenset(r) for r in hedges} == {frozenset(
        {"ts", "event", "id", "replica", "trace_id", "hop"})}
    assert hedges[0]["id"] == "h"


def test_journalless_death_returns_error_results(devices, params):
    """A replica dying WITHOUT a WAL loses its in-flight requests
    honestly — and those error Results come back through the step/
    drain return value, not just poll() (review fix: failover-
    finalized results used to bypass the drain contract)."""
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps)
    reqs = _requests(4, seed=17, budget=8)
    for q in reqs:
        assert router.submit(q)
    owned = [rid for rid, rep in router._owner.items()
             if rep.replica_id == "r0"]
    assert owned
    router.kill_replica("r0")
    finished = router.drain()
    by_id = {r.id: r for r in finished}
    for rid in owned:
        assert by_id[rid].status == "error"
        assert "without a journal" in by_id[rid].error
    # the survivor's requests still completed fine
    for rid, rep in [(q.id, None) for q in reqs]:
        assert rid in by_id


def test_paged_replicas_route_on_page_headroom(devices, params):
    """A PAGED fleet: the router's placement gate consults each
    replica's page headroom (`can_admit_pages`), the health document
    carries the pool occupancy, and outputs stay bit-identical.
    Paged replicas refuse the cluster registry (physical page ids
    cannot cross pools) — asserted loudly."""
    with pytest.raises(ValueError, match="paged"):
        _replica(params, "bad", prefill_chunk=8, prefix_cache_mb=1.0,
                 shared_prefix=PrefixRegistry(8, 1024),
                 kv_page_size=8, kv_pages=8)
    reps = [_replica(params, f"r{i}", prefill_chunk=8,
                     kv_page_size=8, kv_pages=8)
            for i in range(2)]
    router = Router(reps)
    h = reps[0].health()
    assert h["kv_pages_total"] == 8 and h["kv_pages_used"] == 0
    reqs = _requests(4, seed=13, budget=6)
    out = router.run([(0.0, r) for r in reqs])
    assert {r.status for r in out} == {"ok"}
    for q in reqs:
        got = router.poll(q.id)
        assert got.tokens == _serial_tokens(params, q.prompt, 6), q.id


# -- the health surface -----------------------------------------------------


def test_replica_health_document_fields(devices, params):
    rep = _replica(params, "r0", brownout_queue_high=4)
    h = rep.health()
    assert h["replica"] == "r0" and h["state"] == "live"
    assert h["queue_depth"] == 0 and h["load"] == 0
    assert h["free_slots"] == 2 and h["brownout_stage"] == 0
    assert h["kv_pages_total"] is None          # contiguous engine
    assert h["slo_breached"] is False
    assert h["last_tick_age_s"] is None         # never stepped
    rep.server.submit(Request(id="x", prompt=(1, 2),
                              max_new_tokens=2))
    rep.step()
    h = rep.health()
    assert h["last_tick_age_s"] is not None
    rep.drain()
    assert rep.health()["state"] == "draining"
    assert rep.health()["brownout_stage"] == 3  # drain = forced shed


# -- the prefix registry (host-side unit) -----------------------------------


def test_prefix_registry_roundtrip_dedupe_eviction():
    reg = PrefixRegistry(4, 10_000)
    caches = [(np.ones((1, 4, 2, 2), np.float32),
               np.ones((1, 4, 2, 2), np.float32))]
    logits = np.zeros((1, 8), np.float32)
    toks = np.arange(4)
    assert reg.publish(toks, caches, logits)
    assert not reg.publish(toks, caches, logits)       # dedupe
    start, got, lg = reg.lookup(np.arange(8))
    assert start == 4
    assert got[0][0].shape == (1, 4, 2, 2)
    # handed-out arrays are COPIES — mutating them cannot corrupt the
    # stored master
    got[0][0][:] = 7.0
    _, again, _ = reg.lookup(np.arange(8))
    assert float(again[0][0][0, 0, 0, 0]) == 1.0
    assert reg.covered(np.arange(8)) == 4
    assert reg.covered(np.arange(3)) == 0
    with pytest.raises(ValueError):
        reg.publish(np.arange(3), caches, logits)      # off the grid
    # budget eviction: a second distinct prefix evicts the LRU one
    small = PrefixRegistry(4, int(sum(a.nbytes for a in caches[0])
                                  + logits.nbytes))
    assert small.publish(toks, caches, logits)
    assert small.publish(np.arange(10, 14), caches, logits)
    assert small.n_snapshots == 1 and small.evictions == 1


def test_registry_chunk_mismatch_rejected():
    from idc_models_tpu.serve.prefix_cache import PrefixCache

    reg = PrefixRegistry(4, 1024)
    with pytest.raises(ValueError, match="chunk"):
        PrefixCache(8, 1024, shared=reg)


# -- CLI --------------------------------------------------------------------


def test_cli_serve_cluster_smoke(devices, capsys, tmp_path):
    """The serve-cluster verb end to end at smoke scale: 2 decode + 1
    prefill replica, prefix registry, journals, and the kill drill —
    the epilogue must report the migration and the summary line must
    parse."""
    from idc_models_tpu.cli import main

    rc = main([
        "serve-cluster", "--replicas", "2", "--prefill-replicas", "1",
        "--vocab", "11", "--t-max", "32", "--embed-dim", "32",
        "--num-heads", "2", "--mlp-dim", "64", "--num-blocks", "2",
        "--slots", "2", "--window", "4", "--requests", "8",
        "--prefill-chunk", "4", "--prefix-cache-mb", "2",
        "--registry-mb", "8", "--journal-dir", str(tmp_path),
        "--kill-replica", "1", "--kill-after-steps", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "killed replica r1" in out
    assert "migrated onto the survivors" in out
    summary = json.loads(out.split("cluster summary: ", 1)[1]
                         .splitlines()[0])
    assert summary["cluster_requests"] == 8
    assert summary["cluster_replicas_dead"] == 1
    assert summary["cluster_timed_out"] == 0
    assert summary["cluster_handoffs"] >= 1


def test_router_tenant_affinity_and_rehoming(params):
    """ISSUE-14: tenant-tagged requests stick to the replica that last
    served the tenant (prefix-cache/adapter warmth) while the slack
    holds, never override admissibility (a drained home loses the
    tenant), and a dead home rehomes on a survivor."""
    reps = [_replica(params, "r0"), _replica(params, "r1"),
            _replica(params, "r2")]
    router = Router(reps, tenant_affinity_slack=4)

    def place(rid, tenant=None):
        assert router.submit(Request(id=rid, prompt=(1, 2, 3),
                                     max_new_tokens=3, tenant=tenant))
        rep = router._owner[rid]
        router.drain()
        return rep.replica_id

    home = place("a0", "acme")
    # drained between placements, load is equal — affinity (not load)
    # must keep acme where it landed, repeatedly
    assert place("a1", "acme") == home
    assert place("a2", "acme") == home
    # an untagged request still follows pure least-loaded placement
    place("u0")
    # a draining home is not admissible: the tenant moves AND rehomes
    router.drain_replica(home, wait=True)
    other = place("a3", "acme")
    assert other != home
    assert router._tenant_home["acme"].replica_id == other
    # a dead home is forgotten entirely, and the tenant rehomes on a
    # survivor
    router.kill_replica(other)
    assert "acme" not in router._tenant_home
    survivor = place("a4", "acme")
    assert survivor not in (home, other)
