"""Zigzag causal ring attention: exactness, layout round-trip, and the
FLOP-ratio gate.

The zigzag layout (device i holds sequence stripes i and 2n-1-i) is the
load-balanced causal schedule: exactness is pinned against full
attention for ring sizes 1/4/8, values AND gradients, both block impls —
and the claimed ~2x FLOP saving is gated by XLA's own cost analysis of
the compiled programs, not by a docstring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_attention import (
    from_zigzag, full_attention, make_ring_attention, ring_attention,
    to_zigzag, zigzag_indices,
)

B, T, H, D = 2, 64, 2, 8


def _qkv(seed=0, t=T, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, t, H, D)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("n", [1, 4, 8])
def test_zigzag_roundtrip(n):
    x = jnp.arange(2 * T).reshape(1, T, 2).astype(jnp.float32)
    z = to_zigzag(x, n)
    np.testing.assert_array_equal(np.asarray(from_zigzag(z, n)),
                                  np.asarray(x))
    # device i's contiguous shard is [stripe i, stripe 2n-1-i]
    idx = zigzag_indices(T, n)
    sw = T // (2 * n)
    for i in range(n):
        shard = idx[i * 2 * sw:(i + 1) * 2 * sw]
        np.testing.assert_array_equal(
            shard, np.r_[np.arange(i * sw, (i + 1) * sw),
                         np.arange((2 * n - 1 - i) * sw,
                                   (2 * n - i) * sw)])


def test_zigzag_rejects_indivisible():
    with pytest.raises(ValueError, match="not divisible"):
        zigzag_indices(36, 8)


def test_builder_rejects_bad_knobs(devices):
    mesh = meshlib.seq_mesh(8)
    with pytest.raises(ValueError, match="unknown layout"):
        make_ring_attention(mesh, layout="striped")
    with pytest.raises(ValueError, match="unknown block_impl"):
        make_ring_attention(mesh, block_impl="triton")
    # odd local block under zigzag fails at trace with the real message
    q, k, v = _qkv(seed=1, t=8 * 5)   # t_local = 5, odd
    ring = make_ring_attention(mesh, causal=True, layout="zigzag")
    with pytest.raises(ValueError, match="even local block"):
        ring(q, k, v)
    # zigzag + pallas half-block tile check names the 256 rule
    q2, k2, v2 = _qkv(seed=2, t=8 * 128)  # t_local 128 -> quarters 64
    ring2 = make_ring_attention(mesh, causal=True, layout="zigzag",
                                block_impl="pallas")
    with pytest.raises(ValueError, match="256"):
        ring2(q2, k2, v2)


def test_zigzag_permutation_properties():
    """For every (t, n): the indices are a true permutation, and each
    device's shard is [stripe i, stripe 2n-1-i] — so stripe i and its
    mirror always land on the same device (the balance invariant the
    causal schedule's FLOP count rests on)."""
    for n in (1, 2, 3, 4, 5, 8):
        for mult in (1, 2, 5):
            t = 2 * n * mult
            idx = zigzag_indices(t, n)
            assert sorted(idx) == list(range(t))  # permutation
            sw = t // (2 * n)
            per_dev = idx.reshape(n, 2 * sw)
            for i in range(n):
                stripes = set(per_dev[i] // sw)
                assert stripes == {i, 2 * n - 1 - i}, (n, t, i, stripes)
            # inverse really inverts
            inv = np.argsort(idx)
            assert (idx[inv] == np.arange(t)).all()


@pytest.mark.parametrize("n_dev", [8, 4, 1])
def test_zigzag_causal_matches_full(devices, n_dev):
    q, k, v = _qkv()
    mesh = meshlib.seq_mesh(n_dev)
    qz, kz, vz = (to_zigzag(x, n_dev) for x in (q, k, v))
    out = ring_attention(qz, kz, vz, mesh, causal=True, layout="zigzag")
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(from_zigzag(out, n_dev)),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_zigzag_noncausal_matches_full(devices):
    """Without a mask, dense attention is permutation-equivariant: the
    zigzag layout must change nothing."""
    q, k, v = _qkv(seed=11)
    mesh = meshlib.seq_mesh(8)
    qz, kz, vz = (to_zigzag(x, 8) for x in (q, k, v))
    out = ring_attention(qz, kz, vz, mesh, causal=False, layout="zigzag")
    ref = full_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(from_zigzag(out, 8)),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_zigzag_gradients_match_full(devices):
    q, k, v = _qkv(seed=3)
    mesh = meshlib.seq_mesh(8)
    ring = make_ring_attention(mesh, causal=True, layout="zigzag")

    def ring_loss(q, k, v):
        qz, kz, vz = (to_zigzag(x, 8) for x in (q, k, v))
        return jnp.sum(jnp.square(from_zigzag(ring(qz, kz, vz), 8)))

    def full_loss(q, k, v):
        return jnp.sum(jnp.square(full_attention(q, k, v, causal=True)))

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        assert bool(jnp.all(jnp.isfinite(gr))), name
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("n_dev", [8, 4])
def test_zigzag_pallas_matches_full(devices, n_dev):
    """th = t_local/2 must be a 128-multiple for the kernel: t_local is
    pinned to 256, so the quarters sit exactly on the TILE_MIN=128
    boundary; interpret mode on the CPU mesh."""
    q, k, v = _qkv(seed=5, t=256 * n_dev)
    mesh = meshlib.seq_mesh(n_dev)
    qz, kz, vz = (to_zigzag(x, n_dev) for x in (q, k, v))
    ring = make_ring_attention(mesh, causal=True, layout="zigzag",
                               block_impl="pallas")
    out = from_zigzag(ring(qz, kz, vz), n_dev)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block_impl", ["jnp", "pallas"])
@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_unrolled_ring_matches_full(devices, layout, block_impl):
    """`unroll=True` trades program size for cross-step overlap; the
    result must be identical to the fori_loop form — on both block
    engines (the pallas custom_vjp paths share the same run_steps)."""
    t = 2048 if block_impl == "pallas" else T  # kernel tile minimum
    q, k, v = _qkv(seed=13, t=t)
    mesh = meshlib.seq_mesh(8)
    ring = make_ring_attention(mesh, causal=True, layout=layout,
                               block_impl=block_impl, unroll=True)
    if layout == "zigzag":
        args = tuple(to_zigzag(x, 8) for x in (q, k, v))
        out = from_zigzag(ring(*args), 8)
    else:
        out = ring(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def _compiled_flops(fn, *args):
    # the one XLA cost-extraction point (ISSUE 9): program_report
    # normalizes the list-vs-dict cost_analysis() return this helper
    # used to hand-roll
    from idc_models_tpu.observe.profile import program_report

    rep = program_report(jax.jit(fn).lower(*args).compile(),
                         name="zigzag.flop_gate")
    assert rep.flops is not None, "backend reported no FLOPs"
    return rep.flops


def test_zigzag_flop_ratio_gate(devices):
    """THE load-balance claim, gated by XLA's cost analysis: the zigzag
    causal program must execute ~(2n+1)/4n of the contiguous causal
    FLOPs (17/32 ~ 0.53 at n=8). A schedule regression that silently
    computes masked quarters again fails this, independent of wall
    clock (which a 1-chip environment cannot measure for a real ring —
    `experiments/zigzag_bench.py` measures the emulated per-device
    schedule on the TPU instead)."""
    n = 8
    t = 2048  # big enough that attention dominates the permute/mask ops
    q, k, v = _qkv(seed=7, t=t)
    mesh = meshlib.seq_mesh(n)
    # unroll=True: cost analysis only sees the entry computation, and a
    # fori_loop body is opaque to it
    contiguous = make_ring_attention(mesh, causal=True, unroll=True)
    zig = make_ring_attention(mesh, causal=True, layout="zigzag",
                              unroll=True)
    qz, kz, vz = (to_zigzag(x, n) for x in (q, k, v))
    f_cont = _compiled_flops(contiguous, q, k, v)
    f_zig = _compiled_flops(zig, qz, kz, vz)
    ratio = f_zig / f_cont
    expected = (2 * n + 1) / (4 * n)
    assert ratio < expected + 0.08, (
        f"zigzag executes {ratio:.2f}x the contiguous FLOPs; "
        f"expected ~{expected:.2f}")
