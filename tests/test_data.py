"""Data layer tests: loader determinism, splits, sharding, prefetch."""

import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import (
    ArrayDataset, Loader, cifar10, partition, pipeline, synthetic,
)
from idc_models_tpu.data.idc import load_directory, train_val_test_split


@pytest.fixture(scope="module")
def png_tree(tmp_path_factory):
    """A tiny <root>/<label>/*.png tree with recoverable labels."""
    from PIL import Image

    root = tmp_path_factory.mktemp("idc")
    rng = np.random.default_rng(0)
    for label in (0, 1):
        d = root / str(label)
        d.mkdir()
        for i in range(12):
            arr = (rng.random((50, 50, 3)) * 100 + label * 120).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    return root


def test_load_directory_labels_and_range(png_tree):
    ds = load_directory(png_tree, image_size=50, seed=3)
    assert len(ds) == 24
    assert ds.images.dtype == np.float32
    assert 0.0 <= ds.images.min() and ds.images.max() <= 1.0
    assert set(np.unique(ds.labels)) == {0, 1}
    # label is recoverable from brightness (class 1 is brighter)
    bright = ds.images.mean(axis=(1, 2, 3))
    assert bright[ds.labels == 1].mean() > bright[ds.labels == 0].mean()


def test_load_directory_deterministic(png_tree):
    a = load_directory(png_tree, seed=7)
    b = load_directory(png_tree, seed=7)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.images, b.images)
    c = load_directory(png_tree, seed=8)
    assert not np.array_equal(a.labels, c.labels) or not np.array_equal(
        a.images, c.images)


def test_load_directory_resize(png_tree):
    ds = load_directory(png_tree, image_size=10)
    assert ds.images.shape[1:] == (10, 10, 3)


def test_split_is_materialized_and_disjoint():
    imgs, labels = synthetic.make_idc_like(100, size=8, seed=0)
    # tag each image with a unique corner value to detect overlap
    imgs[:, 0, 0, 0] = np.arange(100) / 100.0
    ds = ArrayDataset(imgs, labels)
    tr, va, te = train_val_test_split(ds, (0.8, 0.1, 0.1), seed=5)
    assert (len(tr), len(va), len(te)) == (80, 10, 10)
    ids = np.concatenate([d.images[:, 0, 0, 0] for d in (tr, va, te)])
    assert len(np.unique(ids)) == 100  # disjoint, covers everything


def test_loader_epochs_and_drop_remainder():
    imgs, labels = synthetic.make_idc_like(70, size=8, seed=0)
    ld = Loader(ArrayDataset(imgs, labels), 32, seed=1)
    assert len(ld) == 2
    b0 = list(ld.epoch(0))
    b1 = list(ld.epoch(1))
    assert all(x.shape[0] == 32 for x, _ in b0)
    # different epoch -> different order
    assert not np.array_equal(b0[0][0], b1[0][0])
    # same epoch replayed -> identical
    b0r = list(ld.epoch(0))
    np.testing.assert_array_equal(b0[0][0], b0r[0][0])


def test_loader_repeat_two_passes():
    # the dense preset's repeat(2) (dist_model_tf_dense.py:122-123): each
    # epoch covers the set twice, each pass freshly shuffled
    imgs, labels = synthetic.make_idc_like(64, size=8, seed=0)
    labels = np.arange(64, dtype=np.int32)
    ds = ArrayDataset(imgs, labels)
    ld = Loader(ds, 16, seed=1, repeat=2)
    assert len(ld) == 8
    batches = list(ld.epoch(0))
    assert len(batches) == 8
    first_pass = np.concatenate([y for _, y in batches[:4]])
    second_pass = np.concatenate([y for _, y in batches[4:]])
    # each pass is a full permutation; the two passes are ordered differently
    assert set(first_pass) == set(range(64)) == set(second_pass)
    assert not np.array_equal(first_pass, second_pass)
    # repeat=1 stream is unchanged by the feature (pass 0 seeds the same)
    np.testing.assert_array_equal(
        np.concatenate([y for _, y in Loader(ds, 16, seed=1).epoch(0)]),
        first_pass)
    with pytest.raises(ValueError, match="repeat"):
        Loader(ds, 16, repeat=0)


def test_prefetch_to_mesh_shards(devices):
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(64, size=8, seed=0)
    ld = Loader(ArrayDataset(imgs, labels), 16, shuffle=False)
    out = list(pipeline.prefetch_to_mesh(iter(ld), mesh))
    assert len(out) == 4
    x, y = out[0]
    assert x.shape == (16, 8, 8, 3)
    assert len(x.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(y), labels[:16])


def test_prefetch_propagates_errors(devices):
    mesh = meshlib.data_mesh(8)

    def bad():
        yield (np.zeros((8, 4, 4, 3), np.float32), np.zeros(8, np.int32))
        raise RuntimeError("decode failed")

    it = pipeline.prefetch_to_mesh(bad(), mesh)
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_pad_to_multiple():
    x = np.ones((10, 4, 4, 3), np.float32)
    y = np.ones(10, np.int32)
    px, py, mask = pipeline.pad_to_multiple(x, y, 8)
    assert px.shape[0] == 16 and mask.sum() == 10
    px2, _, mask2 = pipeline.pad_to_multiple(x[:8], y[:8], 8)
    assert px2.shape[0] == 8 and mask2.all()


def test_partition_iid_vs_noniid():
    imgs, labels = synthetic.make_idc_like(400, size=8, seed=0,
                                           pos_fraction=0.5)
    ds = ArrayDataset(imgs, labels)
    ci, cl = partition.partition_clients(ds, 8, iid=True, seed=0)
    assert ci.shape == (8, 50, 8, 8, 3) and cl.shape == (8, 50)
    iid_skew = np.abs(cl.mean(axis=1) - labels.mean()).max()
    _, cl_n = partition.partition_clients(ds, 8, iid=False, seed=0)
    # non-IID: most clients are single-class
    frac = cl_n.mean(axis=1)
    assert np.sum((frac > 0.99) | (frac < 0.01)) >= 6
    assert iid_skew < 0.2


def test_partition_deterministic():
    imgs, labels = synthetic.make_idc_like(64, size=8, seed=0)
    ds = ArrayDataset(imgs, labels)
    a = partition.partition_clients(ds, 4, iid=False, seed=3)
    b = partition.partition_clients(ds, 4, iid=False, seed=3)
    np.testing.assert_array_equal(a[1], b[1])


def test_train_test_client_split():
    tr, te = partition.train_test_client_split(10, 0.2, seed=0)
    assert len(tr) == 8 and len(te) == 2
    assert set(tr) | set(te) == set(range(10))


def test_strided_shard():
    imgs, labels = synthetic.make_idc_like(20, size=8, seed=0)
    labels = np.arange(20, dtype=np.int32)
    ds = ArrayDataset(imgs, labels)
    s = ds.shard(4, 1)
    np.testing.assert_array_equal(s.labels, [1, 5, 9, 13, 17])


def test_cifar10_synthetic_fallback():
    with pytest.warns(UserWarning, match="synthetic stand-in"):
        ds = cifar10.load_cifar10(None, synthetic_size=128)
    assert ds.images.shape == (128, 32, 32, 3)
    assert ds.labels.max() < 10


def test_cifar10_npz(tmp_path):
    x = (np.random.default_rng(0).random((8, 32, 32, 3)) * 255).astype(np.uint8)
    y = np.arange(8) % 10
    np.savez(tmp_path / "cifar10.npz", x_train=x, y_train=y,
             x_test=x[:4], y_test=y[:4])
    ds = cifar10.load_cifar10(str(tmp_path), split="train")
    assert len(ds) == 8
    np.testing.assert_allclose(ds.images, x.astype(np.float32) / 255.0)


def test_filestream_matches_materialized_loader(png_tree):
    """Streaming a directory and training on its materialized
    ArrayDataset (same pair order) must produce identical batch streams
    — FileStream duck-types Loader bit-for-bit."""
    from idc_models_tpu.data.idc import decode_pairs, list_labeled_files

    pairs = list_labeled_files(png_tree)
    stream = pipeline.FileStream(pairs, 50, 8, seed=3)
    labels = np.asarray([l for _, l in pairs], np.int32)
    ds = ArrayDataset(decode_pairs(pairs, 50), labels)
    ld = Loader(ds, 8, seed=3)
    assert len(stream) == len(ld) == 3
    for (sx, sy), (lx, ly) in zip(stream.epoch(1), ld.epoch(1)):
        np.testing.assert_array_equal(sx, lx)
        np.testing.assert_array_equal(sy, ly)
    # repeat passes mirror Loader's seeding too
    s2 = pipeline.FileStream(pairs, 50, 8, seed=3, repeat=2)
    assert len(s2) == 6
    ys = [y for _, y in s2.epoch(0)]
    assert len(ys) == 6
    with pytest.raises(ValueError, match="non-empty"):
        pipeline.FileStream([], 50, 8)
    # replace() re-validates, so fit's schedule path fails as loudly as
    # the constructor would
    with pytest.raises(ValueError, match="repeat"):
        stream.replace(repeat=0)
    with pytest.raises(ValueError, match="batch_size"):
        stream.replace(batch_size=0)
    with pytest.raises(AttributeError):
        stream.replace(nope=1)
    stream.close()  # idempotent even when the pool was never created
    stream.close()


def test_filestream_decode_workers_bit_identical(png_tree):
    """Multi-process decode fan-out (--decode-workers): round-robin
    whole batches over 2 spawned worker processes must yield a stream
    BIT-IDENTICAL to the single-process one, across epochs and repeat
    passes — the parallelism changes throughput, never the data."""
    from idc_models_tpu.data.idc import list_labeled_files

    pairs = list_labeled_files(png_tree)
    base = pipeline.FileStream(pairs, 50, 8, seed=3, repeat=2)
    fanout = pipeline.FileStream(pairs, 50, 8, seed=3, repeat=2,
                                 decode_workers=2)
    try:
        assert len(fanout) == len(base) == 6
        for ep in (0, 1):
            for (sx, sy), (fx, fy) in zip(base.epoch(ep),
                                          fanout.epoch(ep),
                                          strict=True):
                np.testing.assert_array_equal(fx, sx)
                np.testing.assert_array_equal(fy, sy)
        # replace() copies share the worker pool and stay identical
        half = fanout.replace(batch_size=4)
        halfb = base.replace(batch_size=4)
        for (sx, sy), (fx, fy) in zip(halfb.epoch(0), half.epoch(0),
                                      strict=True):
            np.testing.assert_array_equal(fx, sx)
            np.testing.assert_array_equal(fy, sy)
        assert half._proc_box is fanout._proc_box
    finally:
        fanout.close()
        fanout.close()  # idempotent, terminates worker processes once


def test_fit_on_filestream_equals_materialized(png_tree, devices):
    """End-to-end: training from the stream lands on exactly the state
    the materialized path produces."""
    import jax

    from idc_models_tpu.data.idc import decode_pairs, list_labeled_files
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import create_train_state, fit, rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    pairs = list_labeled_files(png_tree)
    labels = np.asarray([l for _, l in pairs], np.int32)
    ds = ArrayDataset(decode_pairs(pairs, 10), labels)
    mesh = meshlib.data_mesh(8)
    model = small_cnn(10, 3, 1)

    def run(train_source):
        opt = rmsprop(1e-3)
        state = create_train_state(model, opt, jax.random.key(0))
        state, hist = fit(model, opt, binary_cross_entropy, state,
                          train_source, None, mesh, epochs=2,
                          batch_size=8, seed=5, verbose=False)
        return jax.device_get(state.params), hist["loss"]

    p_mat, l_mat = run(ds)
    # stream built with a DIFFERENT seed: fit reseeds the schedule to its
    # own (seed=5), so phase seeds apply identically to both paths
    p_str, l_str = run(pipeline.FileStream(pairs, 10, 8, seed=0))
    np.testing.assert_allclose(l_str, l_mat, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_str), jax.tree.leaves(p_mat)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_cifar10_pickle_batches(tmp_path):
    """The cifar-10-batches-py branch: 5 train batches concatenated, CHW
    row-major 3072-vectors transposed to NHWC, /255 scaling."""
    import pickle

    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()

    def make_batch(path, n, label_base):
        # per-image planes: channel c filled with a recoverable constant
        data = np.zeros((n, 3072), np.uint8)
        for i in range(n):
            planes = np.stack([np.full((32, 32), 10 * (c + 1) + i, np.uint8)
                               for c in range(3)])
            data[i] = planes.reshape(-1)
        with open(path, "wb") as f:
            pickle.dump({b"data": data,
                         b"labels": [(label_base + i) % 10 for i in range(n)]},
                        f)

    for b in range(1, 6):
        make_batch(d / f"data_batch_{b}", 4, b)
    make_batch(d / "test_batch", 6, 0)

    train = cifar10.load_cifar10(str(tmp_path), split="train")
    test = cifar10.load_cifar10(str(tmp_path), split="test")
    assert train.images.shape == (20, 32, 32, 3)
    assert test.images.shape == (6, 32, 32, 3)
    assert train.images.dtype == np.float32
    # image 0 of batch 1: channel c == (10*(c+1) + 0)/255 everywhere
    for c in range(3):
        np.testing.assert_allclose(train.images[0, :, :, c],
                                   (10 * (c + 1)) / 255.0)
    # batches concatenate in order: image 4 is batch 2's image 0
    np.testing.assert_allclose(train.images[4, :, :, 0], 10 / 255.0)
    np.testing.assert_array_equal(train.labels[:4], [1, 2, 3, 4])
    np.testing.assert_array_equal(train.labels[4:8], [2, 3, 4, 5])
    np.testing.assert_array_equal(test.labels, np.arange(6) % 10)


def test_prefetch_abandoned_iterator_stops_producer(devices):
    import threading
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(64, size=8, seed=0)
    ld = Loader(ArrayDataset(imgs, labels), 8, shuffle=False)
    n_before = threading.active_count()
    it = pipeline.prefetch_to_mesh(iter(ld), mesh, prefetch=1)
    next(it)
    it.close()  # abandon early
    import time
    for _ in range(50):
        if threading.active_count() <= n_before:
            break
        time.sleep(0.1)
    assert threading.active_count() <= n_before


def test_cifar10_synthetic_splits_differ():
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        tr = cifar10.load_cifar10(None, split="train", synthetic_size=64)
        te = cifar10.load_cifar10(None, split="test", synthetic_size=64)
    assert not np.array_equal(tr.images, te.images)


def test_patchify_token_mapping():
    """patchify: raster-order tokens, each the row-major flatten of one
    sub-patch with channels innermost; patch_size=1 is the per-pixel
    sequence; the token count/width match sequence_shape."""
    from idc_models_tpu.data import sequences

    rng = np.random.default_rng(0)
    imgs = rng.random((2, 6, 6, 3)).astype(np.float32)
    toks = sequences.patchify(imgs, 3)
    assert toks.shape == (2, 4, 27)
    assert toks.shape[1:] == sequences.sequence_shape(6, 3)
    # token 1 = sub-patch at (row 0, col 1); feature order (py, px, c)
    np.testing.assert_array_equal(
        toks[0, 1].reshape(3, 3, 3), imgs[0, 0:3, 3:6, :])
    # token 2 = sub-patch at (row 1, col 0)
    np.testing.assert_array_equal(
        toks[1, 2].reshape(3, 3, 3), imgs[1, 3:6, 0:3, :])
    # per-pixel degenerate case
    pix = sequences.patchify(imgs, 1)
    assert pix.shape == (2, 36, 3)
    np.testing.assert_array_equal(pix[0, 7], imgs[0, 1, 1, :])


def test_patchify_rejections():
    from idc_models_tpu.data import sequences

    with pytest.raises(ValueError, match="divisible"):
        sequences.patchify(np.zeros((1, 6, 6, 3), np.float32), 4)
    with pytest.raises(ValueError, match="N, S, S, C"):
        sequences.patchify(np.zeros((6, 6, 3), np.float32), 2)
    with pytest.raises(ValueError, match=">= 1"):
        sequences.sequence_shape(6, 0)
