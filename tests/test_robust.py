"""Byzantine-robust aggregation (federated/robust.py): order-statistic
correctness against numpy, the influence bound of norm clipping, the
secure-path compatibility gate, and THE acceptance scenario — 3 of 10
clients Byzantine (sign-flip x1000) diverge the weighted mean while
trimmed mean and median keep the server finite and strictly better."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from idc_models_tpu import collectives, faults
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.compat import shard_map
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import pad_clients, partition_clients
from idc_models_tpu.federated import (
    Median, NormClip, TrimmedMean, WeightedMean, get_aggregator,
    initialize_server, make_fedavg_round, make_federated_eval,
)
from idc_models_tpu.models import core, small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy


def _apply_agg(agg, values, weights, n_mesh=4, server=None):
    """Run one aggregator over stacked per-client leaves [C, ...] inside
    the same shard_map environment the round uses."""
    mesh = meshlib.client_mesh(n_mesh)
    if server is None:
        server = jax.tree.map(lambda v: jnp.zeros(v.shape[1:], v.dtype),
                              values)

    def body(vals, w):
        out, metrics = agg(vals, w, server, meshlib.CLIENT_AXIS)
        return out, metrics

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS)),
        out_specs=(P(), P()), check_vma=False)
    out, metrics = jax.jit(mapped)(values,
                                   jnp.asarray(weights, jnp.float32))
    return jax.device_get(out), jax.device_get(metrics)


def test_trimmed_mean_matches_numpy(devices):
    rng = np.random.default_rng(0)
    vals = {"w": rng.normal(size=(8, 5, 3)).astype(np.float32)}
    w = np.array([1, 1, 1, 1, 1, 1, 0, 0], np.float32)  # 2 dead clients
    out, metrics = _apply_agg(TrimmedMean(trim=1), vals, w)
    alive = vals["w"][:6]
    srt = np.sort(alive, axis=0)
    want = srt[1:-1].mean(axis=0)                        # trim 1 per side
    np.testing.assert_allclose(out["w"], want, rtol=1e-6)
    assert "clients_trimmed" in metrics


def test_trimmed_mean_degenerate_band_keeps_server(devices):
    """2*trim >= total slots can NEVER work: rejected at build/trace.
    A live population that dips to n_alive <= 2*trim keeps the incoming
    server state (never the silent all-zero 'mean') and flags it."""
    rng = np.random.default_rng(7)
    vals = {"w": rng.normal(size=(8, 3)).astype(np.float32)}
    with pytest.raises(ValueError, match="can never keep"):
        _apply_agg(TrimmedMean(trim=4), vals, np.ones((8,), np.float32))
    # statically fine (8 slots > 2*2) but only 4 alive at runtime
    w = np.array([1, 1, 1, 1, 0, 0, 0, 0], np.float32)
    server = {"w": jnp.full((3,), 7.0, jnp.float32)}
    out, metrics = _apply_agg(TrimmedMean(trim=2), vals, w,
                              server=server)
    np.testing.assert_array_equal(out["w"], np.full((3,), 7.0))
    assert int(metrics["trim_degenerate"]) == 1
    # and the healthy case reports 0
    _, m_ok = _apply_agg(TrimmedMean(trim=1), vals,
                         np.ones((8,), np.float32))
    assert int(m_ok["trim_degenerate"]) == 0


def test_median_matches_numpy(devices):
    rng = np.random.default_rng(1)
    for n_alive in (5, 6):                               # odd AND even
        vals = {"w": rng.normal(size=(8, 4)).astype(np.float32)}
        w = np.zeros((8,), np.float32)
        w[:n_alive] = 1.0
        out, _ = _apply_agg(Median(), vals, w)
        want = np.median(vals["w"][:n_alive], axis=0)
        np.testing.assert_allclose(out["w"], want, rtol=1e-6)


def test_trimmed_mean_ignores_nonfinite_attackers(devices):
    """With drop_nonfinite unavailable (e.g. the caller disabled it), a
    NaN/Inf client sorts past the kept band: the trimmed mean stays
    finite and equals the honest trimmed mean."""
    rng = np.random.default_rng(2)
    vals = {"w": rng.normal(size=(8, 6)).astype(np.float32)}
    vals["w"][3] = np.inf
    vals["w"][5] = np.nan
    w = np.ones((8,), np.float32)
    out, _ = _apply_agg(TrimmedMean(trim=2), vals, w)
    assert np.all(np.isfinite(out["w"]))
    honest = np.delete(vals["w"], [3, 5], axis=0)
    # 8 alive, trim 2/side -> ranks 2..5; the two non-finite rows occupy
    # the top ranks, so the kept band is ranks 2..5 of the sorted honest
    # values with the worst honest value at rank 5
    srt = np.sort(np.concatenate([honest, np.full((2, 6), np.inf,
                                                  np.float32)]), axis=0)
    np.testing.assert_allclose(out["w"], srt[2:6].mean(axis=0), rtol=1e-6)


def test_norm_clip_bounds_influence(devices):
    """A scaled attacker's delta is clipped to max_norm exactly; honest
    updates below the bound are bit-untouched; the metric counts the
    clipped client."""
    rng = np.random.default_rng(3)
    honest = rng.normal(scale=0.01, size=(8, 10)).astype(np.float32)
    vals = {"w": honest.copy()}
    vals["w"][2] = 100.0                                 # huge delta
    w = np.ones((8,), np.float32)
    out, metrics = _apply_agg(NormClip(max_norm=1.0), vals, w)
    assert int(metrics["clients_clipped"]) == 1
    clipped = vals["w"][2] / np.linalg.norm(vals["w"][2])  # renormed to 1
    want = (honest.sum(0) - honest[2] + clipped) / 8.0
    np.testing.assert_allclose(out["w"], want, rtol=1e-5)


def test_weighted_mean_is_default_and_exact(devices):
    rng = np.random.default_rng(4)
    vals = {"w": rng.normal(size=(8, 3)).astype(np.float32)}
    w = np.array([1, 2, 3, 4, 0, 0, 0, 0], np.float32)
    out, metrics = _apply_agg(WeightedMean(), vals, w)
    want = (vals["w"][:4] * w[:4, None]).sum(0) / w.sum()
    np.testing.assert_allclose(out["w"], want, rtol=1e-6)
    assert metrics == {}
    assert isinstance(get_aggregator(None), WeightedMean)
    with pytest.raises(ValueError, match="unknown aggregator"):
        get_aggregator("krum")


def _tiny_model():
    """Deterministic (dropout-free) tiny model: the Byzantine scenario
    needs speed, not capacity."""
    return core.sequential(
        [
            core.conv2d(3, 8, 3, stride=2, name="conv1"),
            core.relu(),
            core.flatten(),
            core.dense(8 * 5 * 5, 1, name="head"),
        ],
        name="tiny",
    )


def test_byzantine_robustness_acceptance(devices):
    """THE acceptance scenario: 3 of 10 clients Byzantine (sign-flip,
    scale 1000). Under the IDENTICAL fault plan, the weighted mean
    degrades massively while trimmed-mean (trim=3) and median keep the
    server params finite and reach strictly better eval loss; the
    trimmed run replays bit-identically across two builds."""
    n_clients, n_byz = 10, 3
    imgs, labels = synthetic.make_idc_like(n_clients * 16, size=10,
                                           seed=0)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), n_clients,
                               iid=True, seed=0)
    w = np.full((n_clients,), 16.0, np.float32)
    ci, cl, w = pad_clients(ci, cl, w, multiple=8)    # 10 clients, 8 dev
    mesh = meshlib.client_mesh(8)
    model = _tiny_model()
    plan = faults.FaultPlan.byzantine(n_clients, n_byz, kind="sign_flip",
                                      scale=1000.0, seed=7)
    eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)

    def run(agg):
        server = initialize_server(model, jax.random.key(0))
        rnd = make_fedavg_round(model, rmsprop(1e-3),
                                binary_cross_entropy, mesh,
                                local_epochs=1, batch_size=16,
                                aggregator=agg, faults=plan)
        metrics = {}
        for r in range(3):
            server, metrics = rnd(server, ci, cl, w,
                                  jax.random.fold_in(jax.random.key(1),
                                                     r))
        loss = float(eval_fn(server, ci, cl, w)["loss"])
        return jax.device_get(server.params), metrics, loss

    p_mean, _, loss_mean = run(None)
    p_trim, m_trim, loss_trim = run(TrimmedMean(trim=n_byz))
    p_med, _, loss_med = run(Median())

    # robust aggregates stay finite AND strictly beat the mean
    for p in (p_trim, p_med):
        assert all(np.all(np.isfinite(l)) for l in jax.tree.leaves(p))
    assert loss_trim < loss_mean, (loss_trim, loss_mean)
    assert loss_med < loss_mean, (loss_med, loss_mean)
    # the mean demonstrably degraded: orders of magnitude off a sane
    # binary cross entropy (the attackers steered it)
    assert loss_mean > 10 * max(loss_trim, loss_med), loss_mean
    # the trim metric notices at least one attacker
    assert float(m_trim["clients_trimmed"]) >= 1

    # identical fault plan, identical seeds -> bit-identical replay
    p_trim2, _, loss_trim2 = run(TrimmedMean(trim=n_byz))
    assert loss_trim == loss_trim2
    for a, b in zip(jax.tree.leaves(p_trim), jax.tree.leaves(p_trim2)):
        np.testing.assert_array_equal(a, b)


def test_secure_round_aggregator_gate(devices):
    """The masked path rejects plaintext-order-statistic aggregators at
    build time and accepts norm_clip, whose per-client transform rides
    the masked mean (clip metric included)."""
    from idc_models_tpu.secure import make_secure_fedavg_round

    model = small_cnn(10, 3, 1)
    mesh = meshlib.client_mesh(4)
    with pytest.raises(ValueError, match="not compatible with secure"):
        make_secure_fedavg_round(model, rmsprop(1e-3),
                                 binary_cross_entropy, mesh, percent=0.5,
                                 aggregator="trimmed_mean")
    with pytest.raises(ValueError, match="not compatible with secure"):
        make_secure_fedavg_round(model, rmsprop(1e-3),
                                 binary_cross_entropy, mesh, percent=0.5,
                                 aggregator="median")

    imgs, labels = synthetic.make_idc_like(4 * 16, size=10, seed=5)
    ci = imgs.reshape(4, 16, 10, 10, 3)
    cl = labels.reshape(4, 16)
    server = initialize_server(model, jax.random.key(0))
    rnd = make_secure_fedavg_round(
        model, rmsprop(1e-3), binary_cross_entropy, mesh, percent=0.5,
        local_epochs=1, batch_size=16,
        aggregator=NormClip(max_norm=1e-6))   # absurdly tight: clips all
    server, m = rnd(server, ci, cl, jax.random.key(1))
    assert int(m["clients_clipped"]) == 4
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(server.params)))
