"""Ring-sharded KV-cache decode == full causal attention, step by step.

Every decoded token's output must equal the LAST ROW of full causal
attention over the sequence so far (exact attention, distributed
softmax merge) — on 1-D rings of several sizes (incl. non-power-of-2),
on the 2-D ("data", "seq") mesh, and continuing from a `prefill`-placed
prompt bit-identically to having decoded the prompt token by token."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_attention import full_attention
from idc_models_tpu.ring_decode import (
    cache_sharding, init_cache, make_ring_decode, prefill,
)

B, H, D = 2, 2, 8


def _kvq(t, seed=0, b=B):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, t, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _decode_all(mesh, q, k, v, t_max, *, dtype=jnp.float32):
    """Feed tokens one at a time; stack the per-step outputs."""
    b = q.shape[0]
    kc, vc = init_cache(mesh, b, t_max, H, D, dtype=dtype)
    step = make_ring_decode(mesh)
    outs = []
    for pos in range(q.shape[1]):
        tok = slice(pos, pos + 1)
        out, kc, vc = step(kc, vc, q[:, tok], k[:, tok], v[:, tok],
                           pos)
        outs.append(out)
    return jnp.concatenate(outs, axis=1), kc, vc


@pytest.mark.parametrize("n_dev", [1, 3, 4, 8])
def test_decode_matches_full_causal(devices, n_dev):
    t = 24
    q, k, v = _kvq(t, seed=n_dev)
    mesh = meshlib.seq_mesh(n_dev)
    out, _, _ = _decode_all(mesh, q, k, v, t)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_decode_on_2d_mesh(devices):
    """Batch shards over "data" while every data row reduces its own
    ("seq")-sharded cache — DP serving composes like DP training."""
    t = 16
    q, k, v = _kvq(t, seed=9, b=4)
    mesh = meshlib.data_seq_mesh(2, 4)
    out, _, _ = _decode_all(mesh, q, k, v, t)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_decode_partial_cache(devices):
    """t_max larger than the decoded length: empty slots (including
    entire shards nobody has reached yet) contribute exactly zero to
    the merge."""
    t, t_max = 6, 32
    q, k, v = _kvq(t, seed=3)
    mesh = meshlib.seq_mesh(8)   # shards of 4; slots 6..31 empty
    out, _, _ = _decode_all(mesh, q, k, v, t_max)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_prefill_equals_tokenwise(devices):
    """`prefill`-placed prompt K/V + decode of the suffix == decoding
    everything token by token (caches bit-identical, outputs equal)."""
    t, p_len = 16, 10
    q, k, v = _kvq(t, seed=5)
    mesh = meshlib.seq_mesh(4)
    # path A: decode everything
    out_a, kc_a, vc_a = _decode_all(mesh, q, k, v, t)
    # path B: prefill the first p_len, decode the rest
    kc, vc = prefill(mesh, k[:, :p_len], v[:, :p_len], t,
                     dtype=jnp.float32)
    step = make_ring_decode(mesh)
    outs = []
    for pos in range(p_len, t):
        tok = slice(pos, pos + 1)
        out, kc, vc = step(kc, vc, q[:, tok], k[:, tok], v[:, tok], pos)
        outs.append(out)
    np.testing.assert_array_equal(np.asarray(kc), np.asarray(kc_a))
    np.testing.assert_array_equal(np.asarray(vc), np.asarray(vc_a))
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(out_a[:, p_len:]), rtol=1e-6, atol=1e-6)


def test_cache_stays_sharded(devices):
    """The cache keeps its ring sharding through decode steps — no
    device ever holds the full cache (the serving-side O(T/n) claim)."""
    t = 16
    q, k, v = _kvq(t, seed=7)
    mesh = meshlib.seq_mesh(8)
    _, kc, vc = _decode_all(mesh, q, k, v, t)
    want = cache_sharding(mesh)
    assert kc.sharding.is_equivalent_to(want, kc.ndim)
    assert vc.sharding.is_equivalent_to(want, vc.ndim)


def test_decode_bf16_cache(devices):
    """bf16 caches (the serving default) stay within bf16 tolerance of
    the f32 reference — accumulation is f32 inside the merge."""
    t = 12
    q, k, v = _kvq(t, seed=11)
    mesh = meshlib.seq_mesh(4)
    out, _, _ = _decode_all(mesh, q.astype(jnp.bfloat16),
                            k.astype(jnp.bfloat16),
                            v.astype(jnp.bfloat16), t,
                            dtype=jnp.bfloat16)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_decode_rejections(devices):
    mesh = meshlib.seq_mesh(4)
    with pytest.raises(ValueError, match="not divisible"):
        init_cache(mesh, B, 30, H, D)
    with pytest.raises(ValueError, match="ONE token"):
        kc, vc = init_cache(mesh, B, 16, H, D, dtype=jnp.float32)
        q, k, v = _kvq(16)
        make_ring_decode(mesh)(kc, vc, q[:, :2], k[:, :2], v[:, :2], 0)
    with pytest.raises(ValueError, match="exceeds t_max"):
        q, k, v = _kvq(16)
        prefill(mesh, k, v, 8)
    with pytest.raises(ValueError, match="outside the cache"):
        kc, vc = init_cache(mesh, B, 16, H, D, dtype=jnp.float32)
        q, k, v = _kvq(16)
        make_ring_decode(mesh)(kc, vc, q[:, :1], k[:, :1], v[:, :1], 16)
    # a CONCRETE jax scalar must fail the same way, not silently drop
    # the append (no shard owns slot t_max); same for a numpy 0-d array
    for bad in (jnp.int32(16), np.asarray(16)):
        with pytest.raises(ValueError, match="outside the cache"):
            kc, vc = init_cache(mesh, B, 16, H, D, dtype=jnp.float32)
            q, k, v = _kvq(16)
            make_ring_decode(mesh)(kc, vc, q[:, :1], k[:, :1], v[:, :1],
                                   bad)


def test_batched_decode_rowwise_bit_parity(devices):
    """The serving engine's per-row fold: with uniform positions and all
    rows live it is BIT-identical to the scalar fold (same einsums, same
    masking, same merge), and with per-row live masks a dead row's cache
    shard is bit-untouched while live rows still match the scalar
    path."""
    from idc_models_tpu.ring_decode import make_batched_ring_decode

    mesh = meshlib.seq_mesh(4)
    t_max = 16
    kc_a, vc_a = init_cache(mesh, B, t_max, H, D, dtype=jnp.float32)
    kc_b, vc_b = init_cache(mesh, B, t_max, H, D, dtype=jnp.float32)
    dec = make_ring_decode(mesh, jit=False)
    bdec = make_batched_ring_decode(mesh)
    rng = np.random.default_rng(0)

    def tok():
        return (jnp.asarray(rng.normal(0, 1, (B, 1, H, D)), jnp.float32)
                for _ in range(3))

    for pos in range(5):
        q, k, v = tok()
        o_a, kc_a, vc_a = dec(kc_a, vc_a, q, k, v, pos)
        o_b, kc_b, vc_b = bdec(kc_b, vc_b, q, k, v,
                               np.full(B, pos, np.int32),
                               np.ones(B, bool))
        np.testing.assert_array_equal(np.asarray(o_a), np.asarray(o_b))
        np.testing.assert_array_equal(np.asarray(kc_a), np.asarray(kc_b))
    # dead row: row 1 masked out — its shard bit-untouched, row 0 equals
    # the scalar fold's row 0
    q, k, v = tok()
    o_a, kc_a2, _ = dec(kc_a, vc_a, q, k, v, 5)
    o_b, kc_b2, vc_b2 = bdec(kc_b, vc_b, q, k, v,
                             np.array([5, t_max], np.int32),
                             np.array([True, False]))
    np.testing.assert_array_equal(np.asarray(kc_b2)[1],
                                  np.asarray(kc_b)[1])
    np.testing.assert_array_equal(np.asarray(kc_a2)[0],
                                  np.asarray(kc_b2)[0])
    np.testing.assert_array_equal(np.asarray(o_a)[0], np.asarray(o_b)[0])
    # dead rows may sit at pos == t_max (the finished frontier): no
    # crash, no append (checked above); concrete LIVE out-of-range pos
    # is rejected like the scalar path
    with pytest.raises(ValueError, match="outside the cache"):
        bdec(kc_b2, vc_b2, q, k, v, np.array([t_max, 3], np.int32),
             np.array([True, True]))
    with pytest.raises(ValueError, match="one position per row"):
        bdec(kc_b2, vc_b2, q, k, v, np.int32(3), np.ones(B, bool))
