"""Model zoo tests: shapes, parameter-count parity with Keras, masks.

Param-count targets are keras.applications' published totals for
include_top=False backbones (trainable + non-trainable, where
non-trainable = BN moving statistics, which this framework stores in
`state` rather than `params`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models import core, densenet, get_model, mobilenet, vgg


def _count(tree):
    return sum(x.size for x in jax.tree.leaves(tree))


def test_vgg16_param_count_matches_keras():
    bb = vgg.vgg16_backbone()
    v = bb.init(jax.random.key(0))
    assert _count(v.params) == 14_714_688
    assert _count(v.state) == 0  # no BN in VGG16


def test_vgg16_forward_shape():
    m = vgg.vgg16(num_outputs=1)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((2, 50, 50, 3)))
    assert y.shape == (2, 1)


def test_vgg16_fine_tune_mask_selects_block5():
    m = vgg.vgg16(1)
    v = m.init(jax.random.key(0))
    mask = vgg.fine_tune_mask(v.params, 15)
    trainable = sum(p.size for p, t in zip(jax.tree.leaves(v.params),
                                           jax.tree.leaves(mask)) if t)
    # block5: 3 convs 512->512 (2,359,808 each) + head (513)
    assert trainable == 3 * 2_359_808 + 513
    head_mask = vgg.head_only_mask(v.params)
    head_trainable = sum(p.size for p, t in zip(jax.tree.leaves(v.params),
                                                jax.tree.leaves(head_mask)) if t)
    assert head_trainable == 513


@pytest.mark.slow
def test_mobilenet_v2_param_count_matches_keras():
    bb = mobilenet.mobilenet_v2_backbone()
    v = bb.init(jax.random.key(0))
    total = _count(v.params) + _count(v.state)
    assert total == 2_257_984


def test_mobilenet_v2_forward_shape_and_bn_state():
    m = mobilenet.mobilenet_v2(num_outputs=1)
    v = m.init(jax.random.key(0))
    y, new_state = m.apply(v.params, v.state, jnp.ones((2, 50, 50, 3)),
                           train=True)
    assert y.shape == (2, 1)
    # train mode must update BN moving stats somewhere
    changed = any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(v.state), jax.tree.leaves(new_state)))
    assert changed


def test_mobilenet_keras_index_spot_checks():
    idx = mobilenet.KERAS_LAYER_INDEX
    assert idx["Conv1"] == 1
    assert idx["expanded_conv_depthwise"] == 4
    assert idx["block_1_expand"] == 9
    # fine_tune_at=100 splits inside block 11
    assert idx["block_10_project_BN"] < 100 <= idx["block_11_depthwise"]


@pytest.mark.slow
def test_densenet201_param_count_matches_keras():
    bb = densenet.densenet201_backbone()
    v = bb.init(jax.random.key(0))
    total = _count(v.params) + _count(v.state)
    assert total == 18_321_984


@pytest.mark.slow
def test_densenet201_forward_shape():
    m = densenet.densenet201(num_outputs=10)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((1, 32, 32, 3)))
    assert y.shape == (1, 10)


def test_densenet_keras_index_spot_checks():
    idx = densenet.KERAS_LAYER_INDEX
    assert idx["conv1_conv"] == 2
    assert idx["conv2_block1_0_bn"] == 7
    # 150 lands inside conv4_block2 (after 6+12 blocks and two transitions)
    assert idx["conv4_block1_0_bn"] < 150 <= idx["conv4_block2_2_conv"]


def test_registry():
    spec = get_model("vgg16")
    m = spec.build(num_outputs=1)
    v = m.init(jax.random.key(0))
    mask = spec.fine_tune_mask(v.params, spec.default_fine_tune_at)
    assert isinstance(jax.tree.leaves(mask)[0], bool)
    with pytest.raises(KeyError):
        get_model("resnet50")


def test_densenet_stem_symmetric_padding():
    # Keras: ZeroPad(3)+valid conv7/2 -> 112; ZeroPad(1)+valid pool3/2 -> 56
    bb = densenet.densenet201_backbone()
    v = bb.init(jax.random.key(0))
    y, _ = bb.apply(v.params, v.state, jnp.ones((1, 64, 64, 3)))
    assert y.shape == (1, 2, 2, 1920)


def test_mobilenet_frozen_bn_state_static():
    m = mobilenet.mobilenet_v2(1, bn_frozen_below=mobilenet.FREEZE_ALL)
    v = m.init(jax.random.key(0))
    _, new_state = m.apply(v.params, v.state, jnp.ones((2, 32, 32, 3)),
                           train=True)
    for a, b in zip(jax.tree.leaves(v.state), jax.tree.leaves(new_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
