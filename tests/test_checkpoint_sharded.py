"""Sharded checkpointing (checkpoint/sharded.py) against its contracts:

1. MESH PORTABILITY — a checkpoint saved under one layout restores
   bit-identically under ANY other: FSDP -> TP, multi-device -> one
   device, device -> host and back. The manifest stores shapes/dtypes;
   the partition rules are re-resolved against the TARGET mesh.
2. COMPLETION + INTEGRITY — a directory without MANIFEST.json is a
   torn save and is refused; a shard whose bytes fail their manifest
   sha256 is refused. Both with teaching messages.
3. BOUNDED HOST MEMORY — restore assembles each device block from only
   the overlapping saved shards, one shard resident at a time:
   `stats["peak_host_bytes"]` stays around one block + one shard, far
   below the full tree.
"""

import json

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from idc_models_tpu import mesh as meshlib, partition
from idc_models_tpu.checkpoint import (
    MANIFEST_NAME, CheckpointError, checkpoint_info, restore_sharded,
    save_sharded,
)

RULES = partition.PartitionRules((
    (r"w1$", P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)),
    (r"blocks/.*/kernel$", P(None, meshlib.MODEL_AXIS)),
    (r".*", P()),
))


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": rng.normal(size=(64, 32)).astype(np.float32),
        "blocks": {"0": {"kernel": rng.normal(size=(32, 16))
                         .astype(np.float32),
                         "bias": rng.normal(size=(16,))
                         .astype(np.float32)}},
        "step": np.int32(7),
    }


def _placed(tree, mesh):
    return partition.shard_tree(mesh, RULES, tree)


def _assert_identical(restored, host):
    for (n1, a), (n2, b) in zip(partition.tree_paths(restored),
                                partition.tree_paths(host)):
        assert n1 == n2
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(b), err_msg=n1)


def test_cross_mesh_restore_bit_identical(devices, tmp_path):
    """The acceptance core: save under FSDP(4)xTP(2), restore onto a
    pure-TP(8) mesh, a 2-device mesh, and the host — every leaf
    bit-identical every time."""
    host = _tree()
    save_mesh = meshlib.fsdp_tp_mesh(4, 2)
    save_sharded(tmp_path / "ck", _placed(host, save_mesh), step=3)
    info = checkpoint_info(tmp_path / "ck")
    assert info["step"] == 3 and info["n_shards"] >= 8

    for target in (meshlib.fsdp_tp_mesh(1, 8),
                   meshlib.fsdp_tp_mesh(2, 1),
                   meshlib.fsdp_tp_mesh(1, 1)):
        restored = restore_sharded(tmp_path / "ck", mesh=target,
                                   rules=RULES)
        _assert_identical(restored, host)
        # and the layout really is the target's resolution
        spec = restored["w1"].sharding.spec
        assert spec == RULES.specs(host, mesh=target)["w1"]

    _assert_identical(restore_sharded(tmp_path / "ck"), host)


def test_one_device_to_many_and_back(devices, tmp_path):
    """1-dev -> 8-dev and 8-dev -> host round-trips: the save-time
    device count is irrelevant to restore."""
    host = _tree(1)
    one = meshlib.fsdp_tp_mesh(1, 1)
    save_sharded(tmp_path / "one", _placed(host, one))
    wide = restore_sharded(tmp_path / "one",
                           mesh=meshlib.fsdp_tp_mesh(4, 2), rules=RULES)
    _assert_identical(wide, host)

    save_sharded(tmp_path / "wide", wide)
    _assert_identical(restore_sharded(tmp_path / "wide"), host)


def test_torn_manifest_refused(devices, tmp_path):
    """Shard files without MANIFEST.json ARE a torn save: restore and
    checkpoint_info refuse with the completion-contract lesson."""
    save_sharded(tmp_path / "ck",
                 _placed(_tree(), meshlib.fsdp_tp_mesh(2, 2)))
    (tmp_path / "ck" / MANIFEST_NAME).unlink()
    with pytest.raises(CheckpointError,
                       match="completion contract"):
        restore_sharded(tmp_path / "ck")
    with pytest.raises(CheckpointError, match=MANIFEST_NAME):
        checkpoint_info(tmp_path / "ck")


def test_corrupt_shard_refused(devices, tmp_path):
    """A flipped byte in any shard fails that shard's manifest sha256
    at read time — restore refuses rather than assembling garbage."""
    save_sharded(tmp_path / "ck",
                 _placed(_tree(), meshlib.fsdp_tp_mesh(2, 2)))
    manifest = checkpoint_info(tmp_path / "ck")
    victim = manifest["leaves"]["w1"]["shards"][0]["file"]
    raw = bytearray((tmp_path / "ck" / victim).read_bytes())
    raw[0] ^= 0xFF
    (tmp_path / "ck" / victim).write_bytes(raw)
    with pytest.raises(CheckpointError, match="sha256"):
        restore_sharded(tmp_path / "ck",
                        mesh=meshlib.fsdp_tp_mesh(1, 8), rules=RULES)


def test_missing_shard_file_refused(devices, tmp_path):
    save_sharded(tmp_path / "ck",
                 _placed(_tree(), meshlib.fsdp_tp_mesh(2, 2)))
    victim = checkpoint_info(
        tmp_path / "ck")["leaves"]["w1"]["shards"][0]["file"]
    (tmp_path / "ck" / victim).unlink()
    with pytest.raises(CheckpointError, match="missing"):
        restore_sharded(tmp_path / "ck")


def test_peak_host_bytes_bounded_by_shard(devices, tmp_path):
    """The no-O(model)-host-memory gate: restoring onto an 8-way
    sharded mesh never holds more than one target block plus one saved
    shard — far under the full tree."""
    rng = np.random.default_rng(3)
    tree = {"w1": rng.normal(size=(64, 64)).astype(np.float32),
            "blocks": {"0": {"kernel": rng.normal(size=(64, 64))
                             .astype(np.float32)}},
            "step": np.int32(0)}
    total = sum(a.nbytes for _, a in partition.tree_paths(tree))
    save_sharded(tmp_path / "ck",
                 _placed(tree, meshlib.fsdp_tp_mesh(4, 2)))
    stats = {}
    restored = restore_sharded(tmp_path / "ck",
                               mesh=meshlib.fsdp_tp_mesh(8, 1),
                               rules=RULES, stats=stats)
    _assert_identical(restored, tree)
    # largest target block: w1 is 64x64 f32 split 8 ways over rows ->
    # 2 KiB; largest saved shard: w1 split 4x2 -> 2 KiB. Peak must be
    # one block + one shard, not the 32 KiB tree.
    biggest_block = max(sh.data.nbytes
                        for _, leaf in partition.tree_paths(restored)
                        for sh in leaf.addressable_shards)
    biggest_shard = max(
        s["bytes"] for rec in checkpoint_info(
            tmp_path / "ck")["leaves"].values() for s in rec["shards"])
    assert stats["peak_host_bytes"] <= biggest_block + biggest_shard
    assert stats["peak_host_bytes"] < total
    assert stats["bytes_read"] >= total


def test_async_save_and_wait(devices, tmp_path):
    host = _tree(5)
    handle = save_sharded(tmp_path / "ck",
                          _placed(host, meshlib.fsdp_tp_mesh(2, 2)),
                          wait=False)
    manifest = handle.wait(timeout=60)
    assert handle.done() and manifest["n_shards"] > 0
    _assert_identical(restore_sharded(tmp_path / "ck"), host)


def test_mesh_xor_rules_is_an_error(devices, tmp_path):
    save_sharded(tmp_path / "ck", _tree())
    with pytest.raises(CheckpointError, match="BOTH mesh and rules"):
        restore_sharded(tmp_path / "ck",
                        mesh=meshlib.fsdp_tp_mesh(2, 2))
    with pytest.raises(CheckpointError, match="BOTH mesh and rules"):
        restore_sharded(tmp_path / "ck", rules=RULES)


def test_dead_rule_against_checkpoint_refused(devices, tmp_path):
    """A rule matching none of the CHECKPOINT's leaves is the same
    silent-sharding loss shard_tree refuses — caught at restore."""
    save_sharded(tmp_path / "ck", _tree())
    stale = partition.PartitionRules((
        (r"decoder/.*", P(meshlib.MODEL_AXIS)),
        (r".*", P()),
    ))
    with pytest.raises(partition.PartitionError, match="dead"):
        restore_sharded(tmp_path / "ck",
                        mesh=meshlib.fsdp_tp_mesh(2, 2), rules=stale)
    out = restore_sharded(tmp_path / "ck",
                          mesh=meshlib.fsdp_tp_mesh(2, 2), rules=stale,
                          check_dead=False)
    _assert_identical(out, _tree())


def test_template_fixes_structure_and_mismatch_is_loud(devices,
                                                      tmp_path):
    host = _tree(2)
    save_sharded(tmp_path / "ck", host)
    back = restore_sharded(tmp_path / "ck", template=host)
    assert jax.tree_util.tree_structure(
        back) == jax.tree_util.tree_structure(host)
    _assert_identical(back, host)
    with pytest.raises(CheckpointError, match="leaf mismatch"):
        restore_sharded(tmp_path / "ck",
                        template={"w1": host["w1"]})


def test_wrong_format_version_refused(devices, tmp_path):
    save_sharded(tmp_path / "ck", {"a": np.zeros(3, np.float32)})
    mf = tmp_path / "ck" / MANIFEST_NAME
    doc = json.loads(mf.read_text())
    doc["format"] = 99
    mf.write_text(json.dumps(doc))
    with pytest.raises(CheckpointError, match="format"):
        checkpoint_info(tmp_path / "ck")
