"""ISSUE 9 performance-attribution layer (observe/profile.py): the
single program_report extraction point, program registration, the
DeviceTimeline device-vs-host split, roofline verdicts, and the
compile-churn watchdog — plus the armed hooks in fit/run_rounds and
the Generator/SlotEngine program accounts.
"""

import warnings

import numpy as np
import pytest

from idc_models_tpu.observe import MetricsRegistry
from idc_models_tpu.observe import profile as prof


# -- program accounting ------------------------------------------------------


def test_program_report_real_executable(devices):
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((16, 16), jnp.float32)).compile()
    rep = prof.program_report(compiled, name="matmul")
    assert rep.program == "matmul" and rep.available
    assert rep.flops and rep.flops > 0
    assert rep.bytes_accessed and rep.bytes_accessed > 0
    assert rep.arithmetic_intensity == pytest.approx(
        rep.flops / rep.bytes_accessed)
    assert rep.argument_bytes == 16 * 16 * 4
    assert rep.peak_hbm_bytes is not None and rep.peak_hbm_bytes >= 0
    assert rep.missing == ()


class _DeadCompiled:
    """A backend that reports nothing (cost None, memory raises)."""

    def cost_analysis(self):
        return None

    def memory_analysis(self):
        raise NotImplementedError("backend does not expose it")


def test_program_report_degrades_loudly_but_gracefully():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rep = prof.program_report(_DeadCompiled(), name="dead-prog")
    assert not rep.available
    assert rep.flops is None and rep.bytes_accessed is None
    assert rep.peak_hbm_bytes is None
    assert "flops" in rep.missing and "temp_bytes" in rep.missing
    assert any("dead-prog" in str(x.message) for x in w)
    # the roofline verdict for a degraded record is honest: unknown
    v = prof.roofline_verdict(rep, 0.01,
                              spec=prof.BACKEND_ROOFS["v5e"])
    assert v["verdict"] == "unknown" and v["mfu"] is None


def test_register_program_files_table_and_gauges(devices):
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    compiled = jax.jit(lambda x: jnp.sum(x * 2.0)).lower(
        jnp.ones((64,), jnp.float32)).compile()
    cost = prof.register_program("test.reg_prog", compiled,
                                 registry=reg)
    assert prof.registered_programs()["test.reg_prog"] is cost
    g = reg.get("program_flops")
    assert g is not None
    assert g.value(program="test.reg_prog") == cost.flops


def test_register_jit_best_effort(devices):
    import jax.numpy as jnp

    cost = prof.register_jit("test.jit_prog",
                             lambda x: jnp.sum(x ** 2),
                             jnp.ones((8,), jnp.float32))
    assert cost is not None and cost.flops
    assert "test.jit_prog" in prof.registered_programs()

    # a host-side wrapper cannot be lowered: warn + None, never raise
    def hostish(x):
        return float(np.asarray(x).sum())

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = prof.register_jit("test.host_prog", hostish,
                                jnp.ones((4,)))
    assert out is None
    assert any("test.host_prog" in str(x.message) for x in w)
    assert "test.host_prog" not in prof.registered_programs()


# -- DeviceTimeline ----------------------------------------------------------


def _span(name, sid, parent, dur):
    return {"event": "span", "name": name, "id": sid, "parent": parent,
            "tid": 1, "t_ms": float(sid), "dur_ms": float(dur),
            "wall": 0.0, "attrs": {}}


def test_device_timeline_nearest_ancestor_attribution():
    # serve.tick > serve.collect > device.sync: the sync attributes to
    # the tick through the intermediate span; an orphan sync is
    # ignored; fractions sum to 1
    records = [
        _span("serve.tick", 1, None, 10.0),
        _span("serve.collect", 2, 1, 4.0),
        _span("device.sync", 3, 2, 3.0),
        _span("serve.tick", 4, None, 10.0),
        _span("device.sync", 5, 4, 5.0),
        _span("device.sync", 6, None, 99.0),     # no loop ancestor
        _span("train.step", 7, None, 2.0),       # loop without sync
    ]
    reg = MetricsRegistry()
    tl = prof.DeviceTimeline(registry=reg).consume(records)
    rep = tl.report()
    tick = rep["serve.tick"]
    assert tick["steps"] == 2 and tick["wall_ms"] == 20.0
    assert tick["device_ms"] == 8.0 and tick["host_gap_ms"] == 12.0
    assert tick["device_busy_fraction"] == pytest.approx(0.4)
    assert (tick["device_busy_fraction"] + tick["host_gap_fraction"]
            == pytest.approx(1.0))
    assert rep["train.step"]["device_busy_fraction"] == 0.0
    g = reg.get("device_busy_fraction")
    assert g.value(loop="serve.tick") == pytest.approx(0.4)


def test_device_timeline_segments_appended_multi_run_logs():
    """Append-mode logs hold several runs whose span ids restart per
    process — a repeated id starts a new segment, so one run's
    device.sync must never walk parent links into another run's
    spans."""
    run = [
        _span("serve.tick", 1, None, 10.0),
        _span("device.sync", 2, 1, 4.0),
    ]
    # second run reuses ids 1/2 but id 1 is now a NON-loop span: naive
    # whole-file joining would resolve run 1's sync against it
    run2 = [
        _span("other", 1, None, 100.0),
        _span("device.sync", 2, 1, 50.0),
    ]
    rep = prof.DeviceTimeline(registry=MetricsRegistry()).consume(
        run + run2).report()
    tick = rep["serve.tick"]
    assert tick["steps"] == 1 and tick["wall_ms"] == 10.0
    assert tick["device_ms"] == 4.0       # run 2's sync not attributed
    assert tick["device_busy_fraction"] == pytest.approx(0.4)


def test_device_timeline_clamps_device_to_wall():
    # clock jitter can make a child's dur exceed the parent's — the
    # fraction must stay in [0, 1]
    records = [
        _span("fed.round", 1, None, 5.0),
        _span("device.sync", 2, 1, 7.5),
    ]
    rep = prof.DeviceTimeline(registry=MetricsRegistry()).consume(
        records).report()
    assert rep["fed.round"]["device_busy_fraction"] == 1.0
    assert rep["fed.round"]["host_gap_fraction"] == 0.0


# -- roofline ----------------------------------------------------------------


def test_roofline_for_longest_substring_match():
    assert prof.roofline_for("TPU v5 lite").peak_tflops == 197.0
    assert prof.roofline_for("TPU v5p chip").peak_tflops == 459.0
    assert prof.roofline_for("cpu") is None
    spec = prof.register_roof("TestChip9000", 100.0, 1000.0)
    try:
        assert prof.roofline_for("testchip9000 rev2") is spec
    finally:
        del prof.BACKEND_ROOFS[spec.key]
    with pytest.raises(ValueError):
        prof.register_roof("bad", -1.0, 10.0)


def test_roofline_verdict_compute_vs_bandwidth_bound():
    spec = prof.RooflineSpec("x", 100.0, 1000.0)     # ridge = 100 f/B
    hi = prof.ProgramCost(program="hi", flops=1e12, bytes_accessed=1e9,
                          arithmetic_intensity=1000.0)
    lo = prof.ProgramCost(program="lo", flops=1e10, bytes_accessed=1e9,
                          arithmetic_intensity=10.0)
    v = prof.roofline_verdict(hi, 0.1, spec=spec)
    assert v["verdict"] == "compute-bound"
    assert v["achieved_tflops"] == pytest.approx(10.0)
    assert v["mfu"] == pytest.approx(0.1)
    assert v["bound_fraction"] == v["mfu"]
    v = prof.roofline_verdict(lo, 0.01, spec=spec)
    assert v["verdict"] == "bandwidth-bound"
    assert v["achieved_hbm_gbps"] == pytest.approx(100.0)
    assert v["hbm_utilization"] == pytest.approx(0.1)
    assert v["bound_fraction"] == v["hbm_utilization"]
    # n_dev divides whole-program flops back to per-chip
    v2 = prof.roofline_verdict(hi, 0.1, spec=spec, n_dev=2)
    assert v2["achieved_tflops"] == pytest.approx(5.0)
    # unknown backend: verdict unknown, achieved numbers still there
    v3 = prof.roofline_verdict(hi, 0.1, device="cpu")
    assert v3["verdict"] == "unknown"
    assert v3["achieved_tflops"] == pytest.approx(10.0)


# -- compile watchdog --------------------------------------------------------


def test_watchdog_fires_on_shape_varying_recompile_loop(devices):
    """The acceptance drill: a jitted program fed a DIFFERENT shape
    every call recompiles every call — the watchdog flags it past the
    limit. A clean warm run (same shape repeatedly) stays silent."""
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    wd = prof.arm_watchdog(limit=3, registry=reg)
    try:
        f = jax.jit(lambda t: jnp.sum(t * 2.0))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with prof.compiling("drill.varying"):
                for n in range(6):           # 6 shapes -> 6 compiles
                    float(f(jnp.zeros((n + 1,), jnp.float32)))
        churn = [x for x in w if "compile churn" in str(x.message)]
        assert len(churn) == 1               # flags ONCE, not per call
        assert "drill.varying" in str(churn[0].message)
        rep = wd.report()
        assert rep["flagged"] == ["drill.varying"]
        assert rep["programs"]["drill.varying"]["count"] > 3
        assert rep["compile_seconds_total"] > 0
        assert reg.get("compiles_total").value(
            program="drill.varying") > 3
        assert reg.get("compile_churn_flagged_total").value(
            program="drill.varying") == 1
    finally:
        prof.disarm_watchdog()

    # clean warm run: one compile, then cache hits — silent
    wd2 = prof.arm_watchdog(limit=3, registry=MetricsRegistry())
    try:
        g = jax.jit(lambda t: jnp.sum(t + 1.0))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with prof.compiling("drill.warm"):
                for _ in range(10):
                    float(g(jnp.zeros((4,), jnp.float32)))
        assert not [x for x in w if "compile churn" in str(x.message)]
        rep = wd2.report()
        assert rep["flagged"] == []
        assert rep["programs"]["drill.warm"]["count"] <= 3
    finally:
        prof.disarm_watchdog()


def test_watchdog_unnamed_bucket_exempt_and_suppression(devices):
    """The unnamed bucket (unrelated one-shot setup compiles) never
    flags; compiling(None) suppresses recording entirely (accounting
    copies are not churn); disarm stops observation."""
    import jax
    import jax.numpy as jnp

    wd = prof.arm_watchdog(limit=2, registry=MetricsRegistry())
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for n in range(5):               # unnamed: counted, exempt
                float(jax.jit(lambda t: jnp.sum(t - 1.0))(
                    jnp.zeros((n + 10,), jnp.float32)))
        assert not [x for x in w if "compile churn" in str(x.message)]
        rep = wd.report()
        assert rep["flagged"] == []
        assert rep["programs"][prof.UNNAMED]["count"] >= 5
        before = wd.report()["total_compiles"]
        with prof.compiling(None):           # suppressed
            jax.jit(lambda t: t * 3.0).lower(
                jnp.zeros((7,), jnp.float32)).compile()
        assert wd.report()["total_compiles"] == before
    finally:
        prof.disarm_watchdog()
    # disarmed: nothing recorded, naming_compiles is the no-op handle
    after = wd.report()["total_compiles"]
    float(jax.jit(lambda t: jnp.sum(t * 5.0))(
        jnp.zeros((123,), jnp.float32)))
    assert wd.report()["total_compiles"] == after
    assert prof.naming_compiles("x") is prof.naming_compiles("y")


# -- armed hooks in the loops ------------------------------------------------


def test_fit_registers_train_step_when_accounting_armed(devices):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import TrainState, fit, rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.random((16, 10, 10, 3)).astype(np.float32),
                      (rng.random(16) > 0.5).astype(np.int32))
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    prof.PROGRAMS.pop("train.step", None)
    prof.enable_accounting()
    try:
        fit(model, opt, binary_cross_entropy, state, ds, None,
            meshlib.data_mesh(), epochs=1, batch_size=8, verbose=False)
    finally:
        prof.enable_accounting(False)
    cost = prof.registered_programs().get("train.step")
    assert cost is not None and cost.flops


def test_run_rounds_registers_fed_round_when_armed(devices):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.federated.driver import DriverConfig, run_rounds
    from idc_models_tpu.federated.fedavg import ServerState

    def round_fn(server, images, labels, weights, rng):
        new = ServerState(round=server.round + 1,
                          params={"w": server.params["w"] * 0.9},
                          model_state={})
        return new, {"loss": jnp.sum(new.params["w"] ** 2),
                     "accuracy": jnp.float32(0.9)}

    server = ServerState(round=jnp.zeros((), jnp.int32),
                         params={"w": jnp.ones((4,))}, model_state={})
    prof.PROGRAMS.pop("fed.round", None)
    prof.enable_accounting()
    try:
        res = run_rounds(round_fn, server, None, None,
                         np.ones(3, np.float32),
                         config=DriverConfig(rounds=2))
    finally:
        prof.enable_accounting(False)
    assert len(res.history) == 2
    cost = prof.registered_programs().get("fed.round")
    assert cost is not None and cost.available


def test_generator_program_costs(devices):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import Generator, attention_lm

    mesh = meshlib.seq_mesh(1)
    model = attention_lm(16, 32, embed_dim=16, num_heads=2, mlp_dim=32,
                         num_blocks=1, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    gen = Generator(params, embed_dim=16, num_heads=2, num_blocks=1,
                    t_max=32, mesh=mesh, cache_dtype=jnp.float32)
    costs = gen.program_costs(steps=4)
    assert set(costs) == {"lm.prefill", "lm.decode"}
    for cost in costs.values():
        assert cost.available and cost.flops
    assert prof.registered_programs()["lm.prefill"].flops \
        == costs["lm.prefill"].flops
