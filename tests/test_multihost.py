"""Multi-host (DCN) path: 2 OS processes x 4 virtual CPU devices join via
`mesh.initialize_multihost` (jax.distributed) and run a data-parallel
train step whose gradient allreduce crosses the process boundary.

This is the testable stand-in for a multi-host TPU pod (SURVEY.md D5:
ICI within a host, DCN across hosts) — the reference never exercises
multi-node at all (SURVEY.md §4), so this is a capability the framework
adds and must prove.
"""

import re
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dp_step_agrees(tmp_path):
    import os

    # environmental gate (ISSUE 7 satellite): this container's XLA:CPU
    # cannot run multiprocess computations AT ALL — probed once per
    # session with a minimal 2-process psum; the full story lives on
    # the reason string. Runs for real wherever the capability exists.
    from _env_probes import MULTIPROC_SKIP_REASON, multiprocess_cpu_ok

    if not multiprocess_cpu_ok():
        pytest.skip(MULTIPROC_SKIP_REASON)

    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, GRAFT_TEST_CKPT_DIR=str(tmp_path / "ck"))
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), coordinator, "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
    results = {}
    for out in outs:
        m = re.search(r"RESULT proc=(\d+) loss=([-\d.]+) digest=([-\d.]+) "
                      r"eval_loss=([-\d.]+) eval_auroc=([-\d.]+) "
                      r"fed_loss=([-\d.]+) fed_digest=([-\d.]+) "
                      r"sec_loss=([-\d.]+) sec_digest=([-\d.]+) "
                      r"ckpt_loss=([-\d.]+) tp_loss=([-\d.]+) "
                      r"tp_digest=([-\d.]+) sp_digest=([-\d.]+) "
                      r"decode_digest=([-\d.]+)", out)
        assert m, out
        results[int(m.group(1))] = m.groups()[1:]
    assert set(results) == {0, 1}
    # the DP allreduce, the eval logits gather, the FedAvg and
    # secure-aggregation round boundaries, the collective checkpoint
    # save, the cross-process TP step, the ring-attention K/V hops, and
    # the KV-cache decode's pmax/psum merge all spanned processes: both
    # hosts hold identical state and computed identical metrics
    assert results[0] == results[1], results
    # the DP x TP run is the same workload as the DP run in a different
    # layout — its loss must reproduce the DP loss
    assert abs(float(results[0][-4]) - float(results[0][0])) < 1e-4, results
