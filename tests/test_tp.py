"""Channel-wise tensor parallelism over the "model" axis (tp.py).

Beyond-parity capability (the reference is DP-only, SURVEY.md §2b): the
sharding rule splits output channels, GSPMD partitions the step, and a
DP x TP run must match plain DP exactly — same math, different layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from idc_models_tpu import mesh as meshlib, tp
from idc_models_tpu.data import synthetic
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import (
    create_train_state, jit_data_parallel, make_train_step, rmsprop,
    shard_batch,
)
from idc_models_tpu.train.losses import binary_cross_entropy
from idc_models_tpu.train.step import place_state


def _train(mesh, n_steps=8):
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = place_state(mesh,
                        create_train_state(model, opt, jax.random.key(0)))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh)
    imgs, labels = synthetic.make_idc_like(64, size=10, seed=0)
    x, y = shard_batch(mesh, imgs, labels)
    key = jax.random.key(1)
    losses = []
    for _ in range(n_steps):
        key, sub = jax.random.split(key)
        state, m = step(state, x, y, sub)
        losses.append(float(m["loss"]))
    return losses, jax.device_get(state.params)


def test_dp_tp_matches_dp():
    """The same training run on an 8-way DP mesh and a 2x4 DP x TP mesh
    produces the same loss trajectory and parameters: channel sharding
    changes layout, never math (contractions are over unsharded axes)."""
    dp_losses, dp_params = _train(meshlib.data_mesh(8))
    tp_losses, tp_params = _train(tp.dp_tp_mesh(4))
    np.testing.assert_allclose(dp_losses, tp_losses, rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        dp_params, tp_params)
    assert dp_losses[-1] < dp_losses[0]


def test_channel_rule_and_placement():
    """Kernels/biases with model-divisible channel counts shard on the
    last axis; scalars, the Dense(1) head, and odd sizes replicate."""
    assert tp.channel_spec(np.zeros((3, 3, 3, 32)), 4) == P(
        None, None, None, meshlib.MODEL_AXIS)
    assert tp.channel_spec(np.zeros((512, 8)), 4) == P(None,
                                                       meshlib.MODEL_AXIS)
    assert tp.channel_spec(np.zeros((32,)), 4) == P(meshlib.MODEL_AXIS)
    assert tp.channel_spec(np.zeros((512, 1)), 4) == P()   # head
    assert tp.channel_spec(np.zeros(()), 4) == P()         # step counter
    assert tp.channel_spec(np.zeros((7,)), 4) == P()       # odd size

    mesh = tp.dp_tp_mesh(4)
    state = place_state(mesh, create_train_state(
        small_cnn(10, 3, 1), rmsprop(1e-3), jax.random.key(0)))
    kspec = state.params["conv1"]["kernel"].sharding.spec
    assert kspec == P(None, None, None, meshlib.MODEL_AXIS)
    # optimizer moments follow their parameter's layout
    nus = [l for l in jax.tree.leaves(state.opt_state)
           if getattr(l, "ndim", 0) == 4]
    assert nus and all(
        l.sharding.spec == P(None, None, None, meshlib.MODEL_AXIS)
        for l in nus)
    assert state.params["head"]["kernel"].sharding.spec == P()


def test_predict_matches_direct_apply_on_dp_and_tp_mesh():
    """train.predict returns every example's logits in order — equal to
    a direct un-sharded apply — on a DP mesh and a ("data","model") TP
    mesh, including a final partial batch that needs padding."""
    from idc_models_tpu.train import create_train_state, predict

    model = small_cnn(10, 3, 1)
    state = create_train_state(model, rmsprop(1e-3), jax.random.key(0))
    imgs, _ = synthetic.make_idc_like(70, size=10, seed=5)  # 70 % 16 != 0
    want, _ = model.apply(state.params, state.model_state,
                          jnp.asarray(imgs), train=False)
    for mesh in (meshlib.data_mesh(8), tp.dp_tp_mesh(4)):
        got = predict(model, state, imgs, mesh, batch_size=16)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-6)
    # empty input returns an empty array with the right trailing shape
    empty = predict(model, state, imgs[:0], meshlib.data_mesh(8))
    assert empty.shape == (0,) + want.shape[1:]


def test_dp_tp_mesh_validates_degree():
    import pytest

    with pytest.raises(ValueError, match="divide the device count"):
        tp.dp_tp_mesh(16)   # oversize: would make a 0-device data axis
    with pytest.raises(ValueError, match="divide the device count"):
        tp.dp_tp_mesh(3)    # non-dividing: would silently drop devices
    assert tp.dp_tp_mesh(2).devices.size == 8


def test_fit_runs_on_tp_mesh():
    """The full fit loop (loader, prefetch, eval) works unchanged on a
    DP x TP mesh and matches the DP-mesh run."""
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.train.loop import fit
    from idc_models_tpu.train.state import TrainState

    imgs, labels = synthetic.make_idc_like(96, size=10, seed=2)
    train = ArrayDataset(imgs[:64], labels[:64])
    val = ArrayDataset(imgs[64:], labels[64:])
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)

    def run(mesh):
        state = create_train_state(model, opt, jax.random.key(0))
        return fit(model, opt, binary_cross_entropy, state, train, val,
                   mesh, epochs=2, batch_size=16, seed=3, verbose=False)

    _, hist_tp = run(tp.dp_tp_mesh(4))
    _, hist_dp = run(meshlib.data_mesh(8))
    for k in hist_dp:
        np.testing.assert_allclose(hist_dp[k], hist_tp[k], rtol=1e-4)
