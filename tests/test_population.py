"""Population-scale federated training (federated/population.py +
federated/async_fedavg.py): lazy virtual clients, deterministic cohort
sampling, streamed hierarchical aggregation parity, and the buffered
async server — ISSUE 13's tentpole contracts."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import faults as faults_lib
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.federated import (
    ClientPopulation, CohortSampler, DriverConfig, initialize_server,
    make_async_round, make_fedavg_round, make_population_round,
    run_rounds,
)
from idc_models_tpu.federated import robust
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

C = 8          # cohort size shared by most tests


def _population(size=64, seed=3, **kw):
    kw.setdefault("examples_per_client", 16)
    kw.setdefault("image_size", 10)
    return ClientPopulation(size, seed=seed, **kw)


def _model():
    return small_cnn(10, 3, 1)


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(tree))]


def _assert_bitwise(a, b):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y)


def _stream_round(pop, sampler, mesh, wave, **kw):
    kw.setdefault("local_epochs", 1)
    kw.setdefault("batch_size", 16)
    return make_population_round(
        _model(), rmsprop(1e-3), binary_cross_entropy, mesh, pop,
        sampler, wave_size=wave, **kw)


def _async_round(pop, sampler, **kw):
    kw.setdefault("buffer_size", 4)
    kw.setdefault("local_epochs", 1)
    kw.setdefault("batch_size", 16)
    kw.setdefault("seed", 11)
    return make_async_round(_model(), rmsprop(1e-3),
                            binary_cross_entropy, pop, sampler, **kw)


# -- virtual clients ----------------------------------------------------


def test_population_lazy_shards_deterministic():
    pop = _population(32, weight_range=(8.0, 24.0))
    im1, lb1 = pop.shard(5)
    im2, lb2 = _population(32, weight_range=(8.0, 24.0)).shard(5)
    assert im1.tobytes() == im2.tobytes()
    assert lb1.tobytes() == lb2.tobytes()
    assert im1.shape == (16, 10, 10, 3) and lb1.shape == (16,)
    # different client, different seed -> different data
    assert pop.shard(6)[0].tobytes() != im1.tobytes()
    assert _population(32, seed=9,
                       weight_range=(8.0, 24.0)).shard(5)[0].tobytes() \
        != im1.tobytes()
    # seeded weights: in range, deterministic, varied
    ws = pop.all_weights()
    assert ws.shape == (32,)
    assert (ws >= 8.0).all() and (ws <= 24.0).all()
    assert len(np.unique(ws)) > 16
    assert pop.weight(7) == _population(
        32, weight_range=(8.0, 24.0)).weight(7)
    imgs, labels, w = pop.materialize([3, 9, 30])
    assert imgs.shape == (3, 16, 10, 10, 3) and w.shape == (3,)
    np.testing.assert_array_equal(imgs[1], pop.shard(9)[0])
    with pytest.raises(ValueError, match="outside the population"):
        pop.shard(32)
    with pytest.raises(ValueError, match="population"):
        ClientPopulation(0)


def test_cohort_sampler_determinism_and_restart():
    """ISSUE-13 satellite (PR 12 style): same seed => byte-identical
    cohort id sequence across rounds AND across fresh builds (the
    process-restart stand-in; the CLI resume e2e covers a real second
    process); a different seed moves the draw."""
    pop = _population(1000)
    a = CohortSampler(pop, 64, seed=7)
    seq = [a.cohort(r) for r in range(6)]
    for ids in seq:
        assert ids.shape == (64,)
        assert len(np.unique(ids)) == 64          # without replacement
        assert ids.min() >= 0 and ids.max() < 1000
    # restart: a FRESH sampler over a FRESH population object
    b = CohortSampler(_population(1000), 64, seed=7)
    assert b"".join(x.tobytes() for x in seq) == b"".join(
        b.cohort(r).tobytes() for r in range(6))
    # rounds differ from each other, and seed moves the draw
    assert seq[0].tobytes() != seq[1].tobytes()
    moved = CohortSampler(pop, 64, seed=8).cohort(0)
    assert moved.tobytes() != seq[0].tobytes()
    with pytest.raises(ValueError, match="cannot exceed"):
        CohortSampler(pop, 1001)
    # the continuous dispatch stream is deterministic too
    assert [a.client_at(i) for i in range(16)] == \
        [b.client_at(i) for i in range(16)]


def test_weighted_sampler_biases_toward_heavy_clients():
    pop = _population(32, weight_range=(1.0, 16.0))
    s = CohortSampler(pop, 8, seed=5, weighted=True)
    counts = np.zeros(32)
    for r in range(150):
        ids = s.cohort(r)
        assert len(np.unique(ids)) == 8
        counts[ids] += 1
    w = pop.all_weights()
    heavy = counts[w >= np.percentile(w, 75)].mean()
    light = counts[w <= np.percentile(w, 25)].mean()
    assert heavy > 1.5 * light, (heavy, light)
    # deterministic replay
    np.testing.assert_array_equal(
        s.cohort(3), CohortSampler(_population(32, weight_range=(
            1.0, 16.0)), 8, seed=5, weighted=True).cohort(3))


# -- streamed hierarchical aggregation ---------------------------------


def test_streamed_single_wave_bitwise_parity(devices):
    """A single wave covering the cohort runs the IDENTICAL masked-sum
    reduction as the one-shot round: params and model_state come out
    bit-for-bit equal on the same cohort."""
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    rng = jax.random.key(7)
    ids = sampler.cohort(0)
    imgs, labels, w = pop.materialize(ids)
    oneshot = make_fedavg_round(_model(), rmsprop(1e-3),
                                binary_cross_entropy, mesh,
                                local_epochs=1, batch_size=16)
    s1, m1 = oneshot(initialize_server(_model(), jax.random.key(0)),
                     imgs, labels, w, rng)
    stream = _stream_round(pop, sampler, mesh, wave=C)
    s2, m2 = stream(initialize_server(_model(), jax.random.key(0)),
                    None, None, None, rng, round_idx=0)
    _assert_bitwise(s1.params, s2.params)
    _assert_bitwise(s1.model_state, s2.model_state)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]),
                                              rel=1e-6)
    assert int(m2["waves"]) == 1 and int(m2["participants"]) == C


def test_streamed_multiwave_fp_close_and_replays(devices):
    """Splitting the cohort into waves changes only the cross-wave
    ADDITION ORDER: fp-close to the one-shot mean (never a different
    estimator), while the round itself replays bit-identically from
    (seed, round) — the hard ISSUE-13 requirement."""
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    rng = jax.random.key(7)
    ids = sampler.cohort(0)
    imgs, labels, w = pop.materialize(ids)
    oneshot = make_fedavg_round(_model(), rmsprop(1e-3),
                                binary_cross_entropy, mesh,
                                local_epochs=1, batch_size=16)
    s1, _ = oneshot(initialize_server(_model(), jax.random.key(0)),
                    imgs, labels, w, rng)
    stream = _stream_round(pop, sampler, mesh, wave=C // 4)
    s2, m2 = stream(initialize_server(_model(), jax.random.key(0)),
                    None, None, None, rng, round_idx=0)
    assert int(m2["waves"]) == 4
    for a, b in zip(_leaves(s1.params), _leaves(s2.params)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    # bit-identical replay from (seed, round), fresh build
    replay = _stream_round(pop, CohortSampler(pop, C, seed=5), mesh,
                           wave=C // 4)
    s3, _ = replay(initialize_server(_model(), jax.random.key(0)),
                   None, None, None, rng, round_idx=0)
    _assert_bitwise(s2.params, s3.params)
    _assert_bitwise(s2.model_state, s3.model_state)


def test_streamed_norm_clip_composes_exact(devices):
    """NormClip is a per-client transform + weighted mean, so it
    streams losslessly: single-wave streamed == one-shot, bit for bit,
    including the clipped-client count."""
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    rng = jax.random.key(9)
    ids = sampler.cohort(0)
    imgs, labels, w = pop.materialize(ids)
    oneshot = make_fedavg_round(
        _model(), rmsprop(1e-3), binary_cross_entropy, mesh,
        local_epochs=1, batch_size=16,
        aggregator=robust.NormClip(0.05))
    s1, m1 = oneshot(initialize_server(_model(), jax.random.key(0)),
                     imgs, labels, w, rng)
    stream = _stream_round(pop, sampler, mesh, wave=C,
                           aggregator=robust.NormClip(0.05))
    s2, m2 = stream(initialize_server(_model(), jax.random.key(0)),
                    None, None, None, rng, round_idx=0)
    _assert_bitwise(s1.params, s2.params)
    assert float(m1["clients_clipped"]) == float(m2["clients_clipped"])


def test_streamed_trimmed_runs_per_wave(devices):
    """TrimmedMean streams with PER-WAVE semantics: each wave trims its
    own extremes. Under sign-flip attackers the streamed trimmed round
    stays near the honest trajectory while the streamed mean is
    steered far away."""
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    rng = jax.random.key(3)
    ids = sampler.cohort(0)
    # two attackers that ARE in round 0's cohort
    plan = faults_lib.PopulationFaultPlan(pop.size, [
        faults_lib.PopulationFault("sign_flip",
                                   clients=tuple(ids[:2]),
                                   fraction=None, scale=1000.0)])

    def run(agg, faults):
        rnd = _stream_round(pop, CohortSampler(pop, C, seed=5), mesh,
                            wave=C, aggregator=agg, faults=faults)
        s, m = rnd(initialize_server(_model(), jax.random.key(0)),
                   None, None, None, rng, round_idx=0)
        return _leaves(s.params), m

    honest, _ = run(None, None)
    attacked_mean, _ = run(None, plan)
    attacked_trim, mt = run(robust.TrimmedMean(trim=2), plan)
    d_mean = max(np.abs(a - b).max()
                 for a, b in zip(honest, attacked_mean))
    d_trim = max(np.abs(a - b).max()
                 for a, b in zip(honest, attacked_trim))
    assert all(np.isfinite(x).all() for x in attacked_trim)
    assert d_mean > 10 * d_trim, (d_mean, d_trim)
    assert float(mt["trim_degenerate"]) == 0.0


def test_streamed_aggregator_build_teaching_errors():
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    with pytest.raises(ValueError, match="Median cannot stream"):
        _stream_round(pop, sampler, mesh, wave=4,
                      aggregator=robust.Median())
    with pytest.raises(ValueError, match="PER WAVE|per wave|grow "
                                         "wave_size"):
        _stream_round(pop, sampler, mesh, wave=4,
                      aggregator=robust.TrimmedMean(trim=2))
    with pytest.raises(ValueError, match="must divide the cohort"):
        _stream_round(pop, sampler, mesh, wave=3)
    with pytest.raises(ValueError, match="participation mask"):
        rnd = _stream_round(pop, sampler, mesh, wave=4)
        rnd(initialize_server(_model(), jax.random.key(0)), None, None,
            np.ones(5, np.float32), jax.random.key(0), round_idx=0)


def test_streamed_crash_fault_equals_manual_mask(devices):
    """A population-plan crash on a cohort member is bit-identical to
    zeroing that member's participation mask: the virtual-id fault
    lands on exactly the right positional slot."""
    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    mesh = meshlib.client_mesh(1)
    rng = jax.random.key(5)
    ids = sampler.cohort(0)
    victim = int(ids[3])
    plan = faults_lib.PopulationFaultPlan(pop.size, [
        faults_lib.PopulationFault("crash", clients=(victim,),
                                   fraction=None)])
    faulted = _stream_round(pop, CohortSampler(pop, C, seed=5), mesh,
                            wave=C, faults=plan)
    s_f, m_f = faulted(initialize_server(_model(), jax.random.key(0)),
                       None, None, None, rng, round_idx=0)
    mask = np.ones((C,), np.float32)
    mask[3] = 0.0
    plain = _stream_round(pop, CohortSampler(pop, C, seed=5), mesh,
                          wave=C)
    s_m, _ = plain(initialize_server(_model(), jax.random.key(0)),
                   None, None, mask, rng, round_idx=0)
    _assert_bitwise(s_f.params, s_m.params)
    assert int(m_f["clients_dropped"]) == 0    # crash != divergence


def test_streamed_through_driver_checkpoint_resume(devices, tmp_path):
    """ISSUE-13 satellite: the sampler is a pure function of (seed,
    round), so a checkpoint/resume at round r regenerates rounds
    r..R-1's cohorts byte-identically and the resumed run lands on the
    SAME final params as the uninterrupted one — with fresh builder
    objects on the resume side (the process-restart stand-in)."""
    from idc_models_tpu.train import restore_checkpoint

    pop = _population()
    mesh = meshlib.client_mesh(2)

    def builder():
        return _stream_round(_population(), CohortSampler(_population(),
                                                          C, seed=5),
                             mesh, wave=4)

    w = np.ones((C,), np.float32)
    full = run_rounds(builder(),
                      initialize_server(_model(), jax.random.key(0)),
                      None, None, w, config=DriverConfig(rounds=4),
                      seed=1)
    path = tmp_path / "server"
    run_rounds(builder(),
               initialize_server(_model(), jax.random.key(0)),
               None, None, w,
               config=DriverConfig(rounds=2, checkpoint_path=path,
                                   checkpoint_every=2), seed=1)
    restored = restore_checkpoint(
        path, jax.device_get(initialize_server(_model(),
                                               jax.random.key(9))))
    assert int(restored.round) == 2
    resumed = run_rounds(builder(), restored, None, None, w,
                         config=DriverConfig(rounds=4), seed=1)
    assert [h["round"] for h in resumed.history] == [2, 3]
    _assert_bitwise(full.server.params, resumed.server.params)
    _assert_bitwise(full.server.model_state, resumed.server.model_state)


def test_streamed_logs_fed_cohort_events(tmp_path):
    from idc_models_tpu.observe import JsonlLogger

    pop = _population()
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        rnd = _stream_round(pop, CohortSampler(pop, C, seed=5),
                            meshlib.client_mesh(1), wave=4,
                            logger=logger)
        srv = initialize_server(_model(), jax.random.key(0))
        for r in range(2):
            srv, _ = rnd(srv, None, None, None, jax.random.key(r),
                         round_idx=r)
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    cohorts = [r for r in recs if r["event"] == "fed_cohort"]
    assert [r["round"] for r in cohorts] == [0, 1]
    assert cohorts[0]["mode"] == "sync"
    assert cohorts[0]["waves"] == 2 and cohorts[0]["wave_size"] == 4


# -- async buffered FedAvg ---------------------------------------------


def _run_async(rounds=3, pop_kw=(), **kw):
    pop = _population(**dict(pop_kw))
    rf = _async_round(pop, CohortSampler(pop, C, seed=5), **kw)
    srv = initialize_server(_model(), jax.random.key(0))
    history = []
    for r in range(rounds):
        srv, m = rf(srv, None, None, None, None, round_idx=r)
        history.append(m)
    return srv, history, rf


def test_async_full_run_replays_bit_identically():
    s1, h1, _ = _run_async()
    s2, h2, _ = _run_async()
    _assert_bitwise(s1.params, s2.params)
    _assert_bitwise(s1.model_state, s2.model_state)
    assert [m["updates"] for m in h1] == [m["updates"] for m in h2]
    assert [m["staleness_mean"] for m in h1] == \
        [m["staleness_mean"] for m in h2]


def test_async_buffer_and_staleness_semantics():
    # cohort 8, buffer 4: two updates per round, zero leftover; the
    # staleness discount changes the trajectory
    s1, h1, _ = _run_async(staleness_decay=1.0)
    assert all(m["updates"] == 2 for m in h1)
    assert all(m["buffer_fill"] == 0 for m in h1)
    assert h1[-1]["staleness_max"] >= 1       # pipelined in-flight work
    s2, _, _ = _run_async(staleness_decay=0.5)
    different = any(
        (a != b).any() for a, b in zip(_leaves(s1.params),
                                       _leaves(s2.params)))
    assert different, "staleness decay must reweight stale updates"
    # a buffer that does not divide the cohort carries fill across
    # rounds instead of forcing a barrier flush
    pop = _population()
    rf = _async_round(pop, CohortSampler(pop, C, seed=5), buffer_size=5)
    srv = initialize_server(_model(), jax.random.key(0))
    srv, m0 = rf(srv, None, None, None, None, round_idx=0)
    assert m0["updates"] == 1 and m0["buffer_fill"] == 3
    srv, m1 = rf(srv, None, None, None, None, round_idx=1)
    assert m1["updates"] == 2 and m1["buffer_fill"] == 1


def test_async_absorbs_straggler_wall_clock():
    """The acceptance mechanism at unit scale: with an injected
    straggler delay, the sync round's wall is the BARRIER (max delay)
    while the async server processes the fast arrivals — asserted on
    real clocks, driven entirely by the injected sleeps."""
    import time

    pop = _population()
    ids0 = CohortSampler(pop, C, seed=5).cohort(0)
    plan = faults_lib.PopulationFaultPlan(
        pop.size,
        [faults_lib.PopulationFault("straggler",
                                    clients=(int(ids0[0]),),
                                    fraction=None, staleness=2)],
        delay_unit_s=0.3)
    mesh = meshlib.client_mesh(1)
    sync = _stream_round(pop, CohortSampler(pop, C, seed=5), mesh,
                         wave=C, faults=plan, barrier_sleep=True)
    srv = initialize_server(_model(), jax.random.key(0))
    sync(srv, None, None, None, jax.random.key(0), round_idx=0)  # warm
    t0 = time.monotonic()
    srv2 = initialize_server(_model(), jax.random.key(0))
    sync(srv2, None, None, None, jax.random.key(0), round_idx=0)
    sync_wall = time.monotonic() - t0
    assert sync_wall >= 0.6, sync_wall          # 2 lag units slept

    rf = _async_round(pop, CohortSampler(pop, C, seed=5), faults=plan,
                      realtime=True, base_latency_s=(0.001, 0.005))
    srv3 = initialize_server(_model(), jax.random.key(0))
    # round 0 pays the train/apply compiles (the sync side was warmed
    # the same way); round 1 is the steady-state wall the barrier
    # comparison is about
    srv3, _ = rf(srv3, None, None, None, None, round_idx=0)
    t0 = time.monotonic()
    _, m = rf(srv3, None, None, None, None, round_idx=1)
    async_wall = time.monotonic() - t0
    assert m["participants"] == C
    assert async_wall < sync_wall, (async_wall, sync_wall)


def test_async_crash_clients_are_refilled():
    plan = faults_lib.PopulationFaultPlan(
        64, [faults_lib.PopulationFault("crash", fraction=0.25)],
        seed=2)
    _, h, _ = _run_async(faults=plan)
    assert all(m["participants"] == C for m in h)   # slots refilled
    # crashed is a PER-ROUND count, not a lifetime total
    assert sum(m["crashed"] for m in h) > 0
    assert max(m["crashed"] for m in h) < 3 * C


def test_async_retry_discards_the_failed_attempts_inflight_work():
    """Driver rollback isolation: a retried round must NOT apply
    buffered/in-flight updates trained against the discarded attempt's
    params — the async server resets its pool when the round index
    stops advancing."""
    pop = _population()
    rf = _async_round(pop, CohortSampler(pop, C, seed=5),
                      buffer_size=5)   # 5 !| 8: leaves a partial buffer
    calls = []

    def flaky(server, images, labels, weights, rng, *, round_idx=None):
        s, m = rf(server, images, labels, weights, rng,
                  round_idx=round_idx)
        calls.append(round_idx)
        if round_idx == 1 and calls.count(1) == 1:
            s = s.replace(params=jax.tree.map(
                lambda x: x * jnp.nan, s.params))
        return s, m

    res = run_rounds(flaky,
                     initialize_server(_model(), jax.random.key(0)),
                     None, None, np.ones((C,), np.float32),
                     config=DriverConfig(rounds=3), seed=1)
    statuses = [(e["round"], e["status"]) for e in res.events]
    assert (1, "diverged") in statuses
    assert int(res.server.round) == 3
    assert all(np.isfinite(x).all() for x in _leaves(res.server.params))
    # the sharp part: round 0 leaves fill 3 (8 completions, buffer 5).
    # The failed round-1 attempt consumes it (3+8 -> 2 updates, fill
    # 1). The RETRY runs the driver's reseeded subset (6 of 8) and
    # must start from an EMPTY buffer: 6 completions -> 1 update,
    # fill 1; had the discarded attempt's leftover fill carried over,
    # the retry would end at fill 2 — the off-by-the-poisoned-work
    # signature
    assert res.history[0]["updates"] == 1
    assert res.history[0]["buffer_fill"] == 3
    assert res.history[1]["participants"] == 6
    assert res.history[1]["updates"] == 1
    assert res.history[1]["buffer_fill"] == 1


def test_async_through_driver_with_health_events(tmp_path):
    from idc_models_tpu.observe import JsonlLogger

    pop = _population()
    rf = _async_round(pop, CohortSampler(pop, C, seed=5))
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        res = run_rounds(rf,
                         initialize_server(_model(), jax.random.key(0)),
                         None, None, np.ones((C,), np.float32),
                         config=DriverConfig(rounds=2), seed=1,
                         logger=logger)
    assert int(res.server.round) == 2
    assert all(e["status"] == "ok" for e in res.events)
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    assert sum(r["event"] == "round_health" for r in recs) == 2
    assert rf.last_participants.shape == (C,)


def test_async_build_validation_and_secure_rejection():
    from idc_models_tpu.federated import ensure_async_compatible

    pop = _population()
    sampler = CohortSampler(pop, C, seed=5)
    with pytest.raises(ValueError, match="secure"):
        ensure_async_compatible(secure=True)
    with pytest.raises(ValueError, match="secure"):
        _async_round(pop, sampler, secure_aggregation=True)
    with pytest.raises(ValueError, match="TrimmedMean"):
        _async_round(pop, sampler, aggregator=robust.TrimmedMean(1))
    with pytest.raises(ValueError, match="Median"):
        _async_round(pop, sampler, aggregator=robust.Median())
    with pytest.raises(ValueError, match="buffer_size"):
        _async_round(pop, sampler, buffer_size=0)
    with pytest.raises(ValueError, match="staleness_decay"):
        _async_round(pop, sampler, staleness_decay=1.5)
    with pytest.raises(ValueError, match="never fill"):
        _async_round(pop, sampler, buffer_size=C + 1)
    # norm_clip composes (exact per-client transform)
    _async_round(pop, sampler, aggregator=robust.NormClip(1.0))


def test_fed_client_markers_carry_virtual_ids(tmp_path):
    """PR 7 wiring: population rounds stamp fed.client markers with
    VIRTUAL client ids (participant_ids_fn) and the population plan's
    fault outcome."""
    from idc_models_tpu.observe import tracing

    pop = _population(8)
    sampler = CohortSampler(pop, 8, seed=5)     # cohort == population
    ids = sampler.cohort(0)
    straggler = int(ids[2])
    plan = faults_lib.PopulationFaultPlan(
        8, [faults_lib.PopulationFault("straggler",
                                       clients=(straggler,),
                                       fraction=None, staleness=2)])
    rnd = _stream_round(pop, sampler, meshlib.client_mesh(1), wave=8,
                        faults=plan)
    out = tmp_path / "trace.jsonl"
    with tracing(jsonl_path=out):
        run_rounds(rnd, initialize_server(_model(), jax.random.key(0)),
                   None, None, np.ones((8,), np.float32),
                   config=DriverConfig(rounds=1), seed=1,
                   fault_plan=plan,
                   participant_ids_fn=lambda r: sampler.cohort(r))
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    clients = [r for r in recs
               if r.get("name") == "fed.client"]
    got = sorted(r["attrs"]["client"] for r in clients)
    assert got == sorted(int(c) for c in ids)
    marked = [r for r in clients
              if r["attrs"]["client"] == straggler]
    assert marked and marked[0]["attrs"]["fault"] == "straggler"
    assert marked[0]["attrs"]["staleness"] == 2
    ok = [r for r in clients if r["attrs"]["client"] != straggler]
    assert all(r["attrs"]["fault"] == "ok" for r in ok)
