"""Paged KV memory (ISSUE 11) against its hard contracts:

1. INDIRECTION IS INVISIBLE — the page-table-indirect folds and the
   paged engine emit BIT-IDENTICAL tokens to the contiguous path
   (greedy and seeded sampling, chunk boundaries, slot recycling,
   speculative verify, prefix-cache hits) on a 1-device mesh, because
   the gathered logical view presents the same values in the same
   reduction order and the sampling/retirement math is the shared
   `_window_core`/`_verify_core`.
2. PAGES ARE SAFE — dead rows and foreign pages are bit-untouched,
   allocator refcounts balance across 100 recycles (no leak), shared
   prefix pages are never written, and page exhaustion mid-decode
   finishes or retries the starved request honestly without touching a
   neighbor's pages.
3. ZERO RECOMPILATION — mixed page-count traffic after warmup grows no
   jit cache (page tables are VALUES, not shapes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.ring_decode import (
    init_cache, make_batched_ring_decode, make_chunk_ring_decode,
    make_paged_batched_ring_decode, make_paged_chunk_ring_decode,
)
from idc_models_tpu.serve import (
    LMServer, PageAllocator, PagedPrefixCache, PrefixCache, Request,
    RetryPolicy, SlotEngine,
)

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2
PS, PAGES, CHUNK = 4, 24, 8          # the shared paged config


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw(mesh=None):
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)


def _pkw():
    return dict(prefill_chunk=CHUNK, kv_page_size=PS, kv_pages=PAGES)


def _serial_tokens(gen, prompt, steps, *, rng=None):
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps, rng=rng)
    return toks.tolist()[0]


# -- fold level ----------------------------------------------------------


def _pool_from_rows(rows, pt, n_pages, ps):
    """Scatter contiguous [S, T, H, D] rows into a pool per the page
    table — the ground-truth inverse of the fold's gathered view."""
    s, t, h, d = rows.shape
    pool = np.zeros((n_pages, ps, h, d), rows.dtype)
    for b in range(s):
        for j in range(t // ps):
            if pt[b, j] >= 0:
                pool[pt[b, j]] = rows[b, j * ps:(j + 1) * ps]
    return pool


def _rows_from_pool(pool, pt, t):
    s, l = pt.shape
    ps = pool.shape[1]
    out = np.zeros((s, t) + pool.shape[2:], pool.dtype)
    for b in range(s):
        for j in range(l):
            if pt[b, j] >= 0:
                out[b, j * ps:(j + 1) * ps] = pool[pt[b, j]]
    return out


def test_paged_batched_fold_bitwise_matches_contiguous(devices):
    """One-token batched fold: with pages SCATTERED arbitrarily in the
    pool, live rows' outputs and appended K/V are bit-equal to the
    contiguous fold's — and dead rows' pages are bit-untouched."""
    mesh = meshlib.seq_mesh(1)
    S, H, D = 3, 2, 8
    rng = np.random.default_rng(0)
    kc = rng.normal(size=(S, SEQ, H, D)).astype(np.float32)
    vc = rng.normal(size=(S, SEQ, H, D)).astype(np.float32)
    pos = np.array([5, 0, 9], np.int32)
    live = np.array([True, True, False])
    # zero cache content beyond each row's position (the engine
    # invariant the visibility mask rides on)
    for b in range(S):
        kc[b, pos[b]:] = 0.0
        vc[b, pos[b]:] = 0.0
    q = rng.normal(size=(S, 1, H, D)).astype(np.float32)
    kt = rng.normal(size=(S, 1, H, D)).astype(np.float32)
    vt = rng.normal(size=(S, 1, H, D)).astype(np.float32)

    cfold = make_batched_ring_decode(mesh, jit=False)
    out_c, kc2, vc2 = cfold(jnp.asarray(kc), jnp.asarray(vc),
                            jnp.asarray(q), jnp.asarray(kt),
                            jnp.asarray(vt), pos, live)

    # a scattered-but-valid page table: every row's logical pages land
    # on arbitrary distinct physical pages (pool oversized so an
    # unowned page exists)
    l_pages = SEQ // PS
    n_pg = S * l_pages + 4
    perm = rng.permutation(n_pg)[:S * l_pages]
    pt = perm.reshape(S, l_pages).astype(np.int32)
    kp = _pool_from_rows(kc, pt, n_pg, PS)
    vp = _pool_from_rows(vc, pt, n_pg, PS)
    # a poison page no slot owns: must come back bit-identical
    spare = [p for p in range(n_pg) if p not in set(perm.tolist())][0]
    kp[spare] = 7.25
    pfold = make_paged_batched_ring_decode(mesh, page_size=PS,
                                           jit=False)
    out_p, kp2, vp2 = pfold(jnp.asarray(kp), jnp.asarray(vp),
                            jnp.asarray(pt), jnp.asarray(q),
                            jnp.asarray(kt), jnp.asarray(vt), pos,
                            live)
    out_c, out_p = np.asarray(out_c), np.asarray(out_p)
    kp2, vp2 = np.asarray(kp2), np.asarray(vp2)
    # live rows bit-equal (dead row's output is garbage in both paths)
    assert np.array_equal(out_p[live], out_c[live])
    # appended pool content == appended contiguous content, logically
    assert np.array_equal(_rows_from_pool(kp2, pt, SEQ)[live],
                          np.asarray(kc2)[live])
    assert np.array_equal(_rows_from_pool(vp2, pt, SEQ)[live],
                          np.asarray(vc2)[live])
    # the dead row's pages and the unowned page are bit-untouched
    dead = 2
    for j in range(l_pages):
        assert np.array_equal(kp2[pt[dead, j]], kp[pt[dead, j]])
    assert np.array_equal(kp2[spare], kp[spare])


def test_paged_chunk_fold_bitwise_matches_contiguous(devices):
    """Chunk-prefill fold: splicing a chunk through the page table
    yields the same outputs and the same logical cache content as the
    contiguous chunk fold, including the ragged final chunk."""
    mesh = meshlib.seq_mesh(1)
    H, D, C = 2, 8, 8
    rng = np.random.default_rng(1)
    start, p_end = 8, 13                    # ragged: 5 real of 8
    kc = np.zeros((1, SEQ, H, D), np.float32)
    vc = np.zeros((1, SEQ, H, D), np.float32)
    kc[:, :start] = rng.normal(size=(1, start, H, D))
    vc[:, :start] = rng.normal(size=(1, start, H, D))
    q = rng.normal(size=(1, C, H, D)).astype(np.float32)
    kt = rng.normal(size=(1, C, H, D)).astype(np.float32)
    vt = rng.normal(size=(1, C, H, D)).astype(np.float32)

    cfold = make_chunk_ring_decode(mesh, jit=False)
    out_c, kc2, vc2 = cfold(jnp.asarray(kc), jnp.asarray(vc),
                            jnp.asarray(q), jnp.asarray(kt),
                            jnp.asarray(vt), np.int32(start),
                            np.int32(p_end))

    l_pages = SEQ // PS
    pt = rng.permutation(PAGES)[:l_pages].reshape(1, l_pages)
    pt = pt.astype(np.int32)
    kp = _pool_from_rows(kc, pt, PAGES, PS)
    vp = _pool_from_rows(vc, pt, PAGES, PS)
    pfold = make_paged_chunk_ring_decode(mesh, page_size=PS, jit=False)
    out_p, kp2, vp2 = pfold(jnp.asarray(kp), jnp.asarray(vp),
                            jnp.asarray(pt), jnp.asarray(q),
                            jnp.asarray(kt), jnp.asarray(vt),
                            np.int32(start), np.int32(p_end))
    # real queries bit-equal (pad-tail outputs are garbage both sides)
    n_real = p_end - start
    assert np.array_equal(np.asarray(out_p)[:, :n_real],
                          np.asarray(out_c)[:, :n_real])
    got = _rows_from_pool(np.asarray(kp2), pt, SEQ)
    assert np.array_equal(got[:, :p_end], np.asarray(kc2)[:, :p_end])
    # positions past p_end never written (zeros in both)
    assert np.array_equal(got[:, p_end:], np.asarray(kc2)[:, p_end:])


def test_paged_fold_validation(devices):
    mesh = meshlib.seq_mesh(1)
    pfold = make_paged_batched_ring_decode(mesh, page_size=PS,
                                           jit=False)
    kp = jnp.zeros((PAGES, PS, 2, 8))
    pt = jnp.zeros((2, SEQ // PS), jnp.int32)
    q = jnp.zeros((2, 1, 2, 8))
    with pytest.raises(ValueError, match="page dim"):
        pfold(jnp.zeros((PAGES, PS + 1, 2, 8)), kp, pt, q, q, q,
              np.zeros(2, np.int32), np.ones(2, bool))
    with pytest.raises(ValueError, match="ONE token"):
        pfold(kp, kp, pt, jnp.zeros((2, 2, 2, 8)), q, q,
              np.zeros(2, np.int32), np.ones(2, bool))
    with pytest.raises(ValueError, match="one position per"):
        pfold(kp, kp, pt, q, q, q, np.zeros(3, np.int32),
              np.ones(2, bool))
    with pytest.raises(ValueError, match="scales"):
        pfold(kp, kp, pt, q, q, q, np.zeros(2, np.int32),
              np.ones(2, bool), jnp.zeros((PAGES, 2)))
    cfold = make_paged_chunk_ring_decode(mesh, page_size=PS, jit=False)
    with pytest.raises(ValueError, match="multiple of the page"):
        cfold(kp, kp, pt[:1], jnp.zeros((1, PS + 1, 2, 8)),
              jnp.zeros((1, PS + 1, 2, 8)), jnp.zeros((1, PS + 1, 2, 8)),
              np.int32(0), np.int32(0))


# -- allocator -----------------------------------------------------------


def test_page_allocator_refcounts_and_determinism():
    a = PageAllocator(8, 4)
    g1 = a.alloc(3)
    assert g1 == [0, 1, 2] and a.free_count() == 5
    assert a.alloc(6) is None and a.free_count() == 5   # no partial
    a.retain(g1[:1])
    assert a.release(g1) == 2                  # page 0 still shared
    assert a.refcount(0) == 1 and a.free_count() == 7
    assert a.release([0]) == 1 and a.free_count() == 8
    # lowest-free-first: a replayed sequence gets identical placement
    assert a.alloc(2) == [0, 1]
    with pytest.raises(ValueError):
        a.release([5])                         # free page
    with pytest.raises(ValueError):
        a.retain([5])
    with pytest.raises(ValueError):
        PageAllocator(0, 4)


# -- engine / server parity ----------------------------------------------


def test_paged_token_parity_and_no_recompile_greedy(devices, params):
    """The acceptance pair: mixed prompt lengths/budgets through a
    paged server — bit-identical to serial Generator calls, zero jit
    growth after the first wave (page COUNTS vary per request; they
    are values, not shapes), and every page returned at drain."""
    server = LMServer(params, n_slots=3, window=4, **_pkw(), **_kw())
    rng = np.random.default_rng(5)
    reqs = [Request(id=f"r{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + 2 * i)),
                    max_new_tokens=4 + (i % 5) * 2)
            for i in range(8)]
    server.run([(0.0, r) for r in reqs[:2]])
    sizes = server.engine.cache_sizes()
    server.run([(0.0, r) for r in reqs[2:]])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    gen = Generator(params, **_kw())
    for r in reqs:
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert got.tokens == want, (r.id, got.tokens, want)
    assert server.engine._alloc.used_count() == 0
    s = server.summary()
    assert s["serve_kv_pages_total"] == PAGES
    assert 0 < s["serve_kv_pages_used_peak"] <= PAGES
    assert s["serve_kv_tokens_per_hbm_byte"] > 0


def test_paged_seeded_sampling_parity(devices, params):
    server = LMServer(params, n_slots=2, window=4, temperature=1.3,
                      top_k=4, **_pkw(), **_kw())
    rng = np.random.default_rng(9)
    reqs = [Request(id=f"s{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + 3 * i)),
                    max_new_tokens=6, seed=100 + i)
            for i in range(4)]
    server.run([(0.0, r) for r in reqs])
    gen = Generator(params, temperature=1.3, top_k=4, **_kw())
    for r in reqs:
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens,
                              rng=jax.random.key(r.seed))
        assert server.poll(r.id).tokens == want, r.id


def test_paged_chunk_boundary_prompt_lengths(devices, params):
    """Prompt lengths straddling every boundary class: 1, C-1, C, C+1,
    a page-exact length, and the longest admissible prompt."""
    server = LMServer(params, n_slots=2, window=4, **_pkw(), **_kw())
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(3)
    for i, p_len in enumerate([1, CHUNK - 1, CHUNK, CHUNK + 1,
                               2 * PS, SEQ - 2]):
        prompt = tuple(int(x) for x in rng.integers(0, VOCAB, p_len))
        budget = min(3, SEQ - p_len)
        server.run([(0.0, Request(id=f"b{i}", prompt=prompt,
                                  max_new_tokens=budget))])
        want = _serial_tokens(gen, prompt, budget)
        assert server.poll(f"b{i}").tokens == want, p_len


def test_paged_slot_recycle_returns_every_page(devices, params):
    """100 admit/decode/release cycles through 2 slots: the free list
    returns to full every time (no leak), and the last request is
    still bit-identical to serial — recycled pages carry stale content
    that masking must keep invisible."""
    eng = SlotEngine(params, n_slots=2, **_pkw(), **_kw())
    eng.warmup(4)
    rng = np.random.default_rng(7)
    gen = Generator(params, **_kw())
    for i in range(100):
        slot = i % 2
        p_len = 3 + int(rng.integers(0, 8))
        prompt = rng.integers(0, VOCAB, p_len)
        eng.admit(slot, prompt, 2)
        got = []
        while not eng.finished(slot):
            got.extend(eng.step_window(2).get(slot, []))
        eng.release(slot)
        assert eng._alloc.used_count() == 0, i
        if i >= 98:
            assert got == _serial_tokens(gen, tuple(prompt), 2), i


def test_paged_spec_decode_parity(devices, params):
    """Speculative verify through the paged folds: repetitive traffic
    drafts and verifies, outputs stay bit-identical to serial."""
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=4, draft_order=2, **_pkw(), **_kw())
    gen = Generator(params, **_kw())
    reqs = [Request(id=f"p{i}", prompt=tuple([1, 2, 3, 1, 2, 3, 1, 2]),
                    max_new_tokens=10) for i in range(3)]
    server.run([(0.0, r) for r in reqs])
    for r in reqs:
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert server.poll(r.id).tokens == want, r.id
    # speculation genuinely ran (not a silent window fallback)
    assert server.summary()["serve_spec_verify_dispatches"] > 0


def test_paged_int8_deterministic_and_page_capacity(devices, params):
    """int8 pages: per-(page, head) scales are finer than the
    contiguous per-slot ones, so the gates are determinism (identical
    runs bit-identical), bounded drift vs the float paged engine, and
    the page-byte capacity ratio."""
    rng = np.random.default_rng(3)
    reqs = [Request(id=f"q{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 5 + i)),
                    max_new_tokens=6) for i in range(3)]
    outs = []
    for _ in range(2):
        srv = LMServer(params, n_slots=2, window=4, kv_dtype="int8",
                       **_pkw(), **_kw())
        srv.run([(0.0, r) for r in reqs])
        outs.append({r.id: tuple(srv.poll(r.id).tokens) for r in reqs})
    assert outs[0] == outs[1]
    f32 = SlotEngine(params, n_slots=2, **_pkw(), **_kw())
    i8 = SlotEngine(params, n_slots=2, kv_dtype="int8", **_pkw(),
                    **_kw())
    # int8 pages cost ~1/4 the f32 page (scales are the small +)
    assert f32.kv_page_bytes() / i8.kv_page_bytes() >= 3.0
    # drift check at the logits level: same request, final logits of
    # int8-paged close to f32-paged (the PR-4 int8 contract, per page)
    f32.admit(0, np.asarray(reqs[0].prompt), 4)
    i8.admit(0, np.asarray(reqs[0].prompt), 4)
    f32.step_window(4), i8.step_window(4)
    lf = np.asarray(f32._logits[0], np.float32)
    li = np.asarray(i8._logits[0], np.float32)
    assert np.max(np.abs(lf - li)) < 0.35 * max(np.max(np.abs(lf)), 1)


def test_paged_prefix_sharing_zero_copy_and_parity(devices, params):
    """Two requests sharing a 16-token prefix: the snapshot shares the
    FIRST request's pages (refcounted — no copies), the second request
    allocates fewer fresh pages, both outputs bit-identical to
    serial, and release + eviction return every page."""
    server = LMServer(params, n_slots=2, window=4, prefix_cache_mb=64.0,
                      **_pkw(), **_kw())
    eng = server.engine
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(11)
    pre = tuple(int(x) for x in rng.integers(0, VOCAB, 2 * CHUNK))
    r1 = Request(id="a", prompt=pre + (1, 2), max_new_tokens=4)
    server.run([(0.0, r1)])
    pc = eng.prefix_cache
    assert pc.n_snapshots >= 1 and pc.cached_pages() > 0
    # snapshot pages are SHARED refs on pool pages, not copies: the
    # deepest snapshot's pages are refcounted in the allocator
    shared_before = pc.cached_pages()
    used_between = eng._alloc.used_count()
    assert used_between == shared_before        # only the cache holds
    r2 = Request(id="b", prompt=pre + (3, 4, 5), max_new_tokens=4)
    server.run([(0.0, r2)])
    assert pc.hits >= 1
    for r in (r1, r2):
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert server.poll(r.id).tokens == want, r.id
    # a shared page held by cache + (released) slots: refcount balance
    # leaves exactly the cache's references at drain
    assert eng._alloc.used_count() == pc.cached_pages()
    # evict everything: the pool drains to empty
    freed = pc.reclaim(PAGES)
    assert freed == pc.cached_pages() or pc.n_snapshots == 0
    assert eng._alloc.used_count() == 0


def test_paged_prefix_reclaim_spares_slot_pinned_snapshots():
    """Pool-pressure reclaim ranks FREEABILITY above LRU: a snapshot
    whose pages live slots still share frees nothing and is never
    evicted by reclaim (destroying a hit-proven shared prefix for
    zero pages), while a freeable one goes regardless of its rank."""
    a = PageAllocator(8, 4)
    pc = PagedPrefixCache(CHUNK, max_pages=8)
    pc.bind(a, 64)
    slot1 = a.alloc(2)                  # a "live slot" holds these
    pc.insert([1] * CHUNK, slot1, np.zeros((1, 4), np.float32))
    pc.lookup([1] * CHUNK)              # hit-proven AND older
    slot2 = a.alloc(2)
    pc.insert([2] * CHUNK, slot2, np.zeros((1, 4), np.float32))
    a.release(slot2)                    # its slot finished: exclusive
    assert pc.reclaimable_pages() == 2
    assert pc.reclaim(1) == 2           # evicts the FREEABLE snapshot
    assert pc.n_snapshots == 1
    assert pc.lookup([1] * CHUNK)[0] == CHUNK    # pinned one survives
    # nothing else is freeable: reclaim refuses to destroy it
    assert pc.reclaim(4) == 0
    assert pc.n_snapshots == 1


def test_paged_prefix_cache_rebind_drops_stale_pages(devices, params):
    """Warm-restart: rebinding a populated paged cache to a NEW
    engine's allocator must drop every snapshot — the stored page ids
    name the dead pool's pages, and carrying them over would
    retain/corrupt pages the new allocator grants to live requests.
    The rebuilt server starts cold, re-warms, and stays
    bit-identical."""
    pc = PagedPrefixCache(CHUNK, max_pages=16)
    kw = _kw()
    srv_a = LMServer(params, n_slots=2, window=4, prefix_cache=pc,
                     **_pkw(), **kw)
    gen = Generator(params, **kw)
    rng = np.random.default_rng(29)
    pre = tuple(int(x) for x in rng.integers(0, VOCAB, 2 * CHUNK))
    srv_a.run([(0.0, Request(id="a", prompt=pre + (1,),
                             max_new_tokens=4))])
    assert pc.n_snapshots > 0
    srv_a.close()
    # the "crashed" engine is gone; a rebuilt server reuses the cache
    srv_b = LMServer(params, n_slots=2, window=4, prefix_cache=pc,
                     **_pkw(), **kw)
    assert pc.n_snapshots == 0 and pc.cached_pages() == 0   # cold
    r1 = Request(id="b1", prompt=pre + (2,), max_new_tokens=4)
    r2 = Request(id="b2", prompt=pre + (3,), max_new_tokens=4)
    srv_b.run([(0.0, r1)])
    srv_b.run([(0.0, r2)])
    assert pc.hits >= 1                 # re-warmed on the new pool
    for r in (r1, r2):
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert srv_b.poll(r.id).tokens == want, r.id


def test_paged_prefix_eviction_under_page_budget(devices, params):
    """A 4-page snapshot budget under many distinct prefixes: the LRU
    evicts, the budget holds, and a hit after evict re-prefills with
    bit-identical output (never stale)."""
    pc = PagedPrefixCache(CHUNK, max_pages=4)
    server = LMServer(params, n_slots=2, window=4, prefix_cache=pc,
                      **_pkw(), **_kw())
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(13)
    prompts = [tuple(int(x) for x in rng.integers(0, VOCAB, CHUNK))
               + (i,) for i in range(4)]
    for i, p in enumerate(prompts):
        server.run([(0.0, Request(id=f"e{i}", prompt=p,
                                  max_new_tokens=3))])
    assert pc.evictions > 0
    assert pc.cached_pages() <= 4
    # the first prefix was evicted — a re-run misses, re-prefills, and
    # still matches serial bit-for-bit
    server.run([(0.0, Request(id="again", prompt=prompts[0],
                              max_new_tokens=3))])
    assert (server.poll("again").tokens
            == _serial_tokens(gen, prompts[0], 3))


def test_page_exhaustion_mid_decode_is_honest(devices, params):
    """A small pool + a small decode reserve forces mid-decode growth
    to fail: the starved request retries (restarting bit-identically)
    or finishes with an honest error — and the surviving neighbor's
    output is untouched. Every page returns at drain."""
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(17)
    pa = tuple(int(v) for v in rng.integers(0, VOCAB, 8))
    pb = tuple(int(v) for v in rng.integers(0, VOCAB, 8))
    ra = Request(id="x", prompt=pa, max_new_tokens=20)
    rb = Request(id="y", prompt=pb, max_new_tokens=20)
    # with retries: one request wins the pool race, the other retries
    # once pages free — BOTH eventually ok and bit-identical
    srv = LMServer(params, n_slots=2, window=4, prefill_chunk=CHUNK,
                   kv_page_size=PS, kv_pages=8, kv_decode_reserve=4,
                   retry=RetryPolicy(max_retries=4, backoff_s=0.0),
                   **_kw())
    srv.run([(0.0, ra), (0.0, rb)])
    assert srv.summary()["serve_slot_faults"] > 0    # exhaustion fired
    n_ok = 0
    for r in (ra, rb):
        got = srv.poll(r.id)
        if got.status == "ok":
            n_ok += 1
            assert got.tokens == _serial_tokens(gen, r.prompt, 20), r.id
    assert n_ok >= 1
    assert srv.engine._alloc.used_count() == 0
    # without retries: the starved request finishes error/slot_fault
    # honestly; the survivor is still bit-identical
    srv2 = LMServer(params, n_slots=2, window=4, prefill_chunk=CHUNK,
                    kv_page_size=PS, kv_pages=8, kv_decode_reserve=4,
                    **_kw())
    srv2.run([(0.0, ra), (0.0, rb)])
    statuses = {r.id: srv2.poll(r.id).status for r in (ra, rb)}
    assert "error" in statuses.values()
    for r in (ra, rb):
        got = srv2.poll(r.id)
        if got.status == "ok":
            assert got.tokens == _serial_tokens(gen, r.prompt, 20)
        else:
            assert got.finish_reason == "slot_fault"
    assert srv2.summary()["serve_page_exhaustions"] > 0
    assert srv2.engine._alloc.used_count() == 0


def test_paged_admission_backpressure_feeds_brownout(devices, params):
    """A pool that fits one request at a time: the queue head WAITS on
    pages (page-aware admission — no refusal, no corruption), the
    exhaustion is counted, the brownout controller escalates with the
    'pages' reason, and everything still finishes bit-identically."""
    from idc_models_tpu.serve import BrownoutController

    bo = BrownoutController(queue_high=10_000, clamp_tokens=4,
                            escalate_dwell_s=0.0, clear_after_s=60.0)
    srv = LMServer(params, n_slots=2, window=4, prefill_chunk=CHUNK,
                   kv_page_size=PS, kv_pages=8, brownout=bo, **_kw())
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(19)
    reqs = [Request(id=f"w{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 10)),
                    max_new_tokens=16) for i in range(3)]
    srv.run([(0.0, r) for r in reqs])
    s = srv.summary()
    assert s["serve_page_exhaustions"] > 0
    assert any("pages" in t["reason"] for t in bo.transitions)
    for r in reqs:
        got = srv.poll(r.id)
        assert got.status == "ok"
        # brownout stage 2 may clamp budgets — parity at the SERVED
        # length (the clamp is an admission policy, not corruption)
        n = len(got.tokens)
        assert got.tokens == _serial_tokens(gen, r.prompt, 16)[:n]


def test_paged_release_kills_zombie_row(devices, params):
    """Releasing a slot MID-RUN (deadline cancel) must kill its device
    row in the same dispatch its pages free: the freed pages are
    re-granted immediately, and a still-live row writing through its
    stale page table would corrupt the new owner (the contiguous
    ride-along contract does not transfer to a shared pool)."""
    eng = SlotEngine(params, n_slots=2, **_pkw(), **_kw())
    eng.warmup(4)
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(23)
    pa = rng.integers(0, VOCAB, 4)
    eng.admit(0, pa, 24)                      # long budget
    eng.step_window(2)                        # decode a little
    eng.release(0)                            # cancel with ~22 left
    assert eng._alloc.used_count() == 0
    assert int(np.asarray(eng._rem)[0]) == 0  # device row KILLED
    # a LONG-prompt request takes the freed pages in the OTHER slot:
    # without the kill, the cancelled row (position BEHIND the new
    # owner's) would keep appending through its stale table straight
    # into the new owner's already-attended prompt pages (A/B-verified
    # against the old release semantics — prompt region diverges)
    pb = rng.integers(0, VOCAB, 16)
    eng.admit(1, pb, 8)

    def prompt_region():
        kp = np.asarray(eng._caches[0][0])
        pt = np.asarray(eng._pt)[1]
        return np.stack([kp[pt[j]] for j in range(16 // PS)])

    before = prompt_region()
    got = []
    while not eng.finished(1):
        got.extend(eng.step_window(2).get(1, []))
    assert np.array_equal(before, prompt_region())  # pages untouched
    eng.release(1)
    assert got == _serial_tokens(gen, tuple(pb), 8)


def test_paged_deadline_cancel_frees_prefill_grant(devices, params):
    """A request cancelled while still chunking returns its whole
    grant — nothing ever reached the batch row."""
    eng = SlotEngine(params, n_slots=1, **_pkw(), **_kw())
    eng.warmup(2)
    eng.start_prefill(0, np.arange(20) % VOCAB, 4)
    assert eng._alloc.used_count() > 0
    eng.prefill_step(0)                       # one chunk in
    eng.cancel_prefill(0)
    assert eng._alloc.used_count() == 0
    assert eng.free_slots() == [0]


def test_paged_validation_errors(devices, params):
    with pytest.raises(ValueError, match="BOTH kv_page_size"):
        SlotEngine(params, kv_page_size=PS, **_kw())
    with pytest.raises(ValueError, match="chunked prefill"):
        SlotEngine(params, kv_page_size=PS, kv_pages=PAGES, **_kw())
    with pytest.raises(ValueError, match="divide t_max"):
        SlotEngine(params, prefill_chunk=CHUNK, kv_page_size=5,
                   kv_pages=PAGES, **_kw())
    with pytest.raises(ValueError, match="multiple of kv_page_size"):
        SlotEngine(params, prefill_chunk=2, kv_page_size=4,
                   kv_pages=PAGES, **_kw())
    with pytest.raises(ValueError, match="could never be admitted"):
        SlotEngine(params, prefill_chunk=CHUNK, kv_page_size=PS,
                   kv_pages=SEQ // PS - 1, **_kw())
    with pytest.raises(ValueError, match="kv_decode_reserve"):
        SlotEngine(params, kv_decode_reserve=4, **_kw())
    with pytest.raises(ValueError, match="flavor must match"):
        SlotEngine(params, prefix_cache=PrefixCache(CHUNK, 1 << 20),
                   **_pkw(), **_kw())
    with pytest.raises(ValueError, match="flavor must match"):
        SlotEngine(params, prefill_chunk=CHUNK,
                   prefix_cache=PagedPrefixCache(CHUNK, max_pages=4),
                   **_kw())
    with pytest.raises(ValueError, match="exactly one"):
        PagedPrefixCache(CHUNK)
    with pytest.raises(ValueError, match="exactly one"):
        PagedPrefixCache(CHUNK, max_pages=4, budget_mb=1.0)


def test_paged_kv_resident_accounting(devices, params):
    """kv_bytes_resident tracks pages, not slots: a short resident
    request costs its pages only, and the tokens-per-HBM-byte figure
    beats the contiguous engine's reservation arithmetic."""
    eng = SlotEngine(params, n_slots=4, **_pkw(), **_kw())
    eng.warmup(2)
    contig = SlotEngine(params, n_slots=4, **_kw())
    assert eng.kv_bytes_resident() == 0
    eng.admit(0, np.arange(5) % VOCAB, 3)     # 8 tokens -> 2 pages
    assert eng._alloc.used_count() == 2
    assert eng.kv_bytes_resident() == 2 * eng.kv_page_bytes()
    stats = eng.page_stats()
    assert stats["pages_total"] == PAGES
    assert stats["pages_used"] == 2
    assert stats["resident_tokens"] == 5
    # the contiguous engine reserves 4 full rows no matter what
    assert contig.page_stats() is None
    assert (contig.kv_bytes_resident()
            == 4 * contig.kv_bytes_per_slot())
    assert eng.kv_bytes_resident() < contig.kv_bytes_resident()
    eng.release(0)
    assert eng.kv_bytes_resident() == 0
