"""Zero-downtime weight rollout (checkpoint/rollout.py + the serve
hooks) against its contracts:

1. ZERO DROP/DUP — a trace replayed through `run_with_rollout` comes
   back with exactly one Result per request id, every one served,
   whether the rollout promotes or rolls back.
2. FORCED-BAD CANDIDATE — a NaN candidate is caught at the STAGING
   spot-check on the engine's already-compiled programs and
   auto-rolls back with zero client-visible errors (no request ever
   routes onto the bad weights).
3. PROMOTE SEMANTICS — `swap_params` is zero-recompile (jit cache
   sizes frozen across the swap), refuses architecture changes with a
   teaching error, and post-promote output matches a server BUILT on
   the candidate weights bit-for-bit.
4. ADAPTER FIRST RUNG — `swap_adapters` changes a tenant's stream to
   match a natively-built bank, and teaches on tenant-less servers.
5. FLEET SCALE — `Router.start_rollout` canaries ONE replica, the
   health-document decision promotes the rest or swaps back.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.checkpoint import (
    RolloutController, run_with_rollout, save_sharded,
)
from idc_models_tpu.checkpoint.rollout import RolloutError
from idc_models_tpu.models.lm import attention_lm
from idc_models_tpu.serve import LMServer, Request, TenantRegistry
from idc_models_tpu.serve.cluster import Router, build_replica

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


@pytest.fixture(scope="module")
def candidate():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(1)).params


def _kw(**over):
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, cache_dtype=jnp.float32)
    kw.update(over)
    return kw


def _trace(n, *, start=0, tenant=None, seed=7):
    rng = np.random.default_rng(seed)
    return [(0.0, Request(id=f"r{start + i}",
                          prompt=tuple(int(x) for x in
                                       rng.integers(1, VOCAB,
                                                    3 + i % 5)),
                          max_new_tokens=3 + i % 4, tenant=tenant))
            for i in range(n)]


def _assert_one_result_each(results, trace):
    ids = [r.id for r in results]
    assert sorted(ids) == sorted(t[1].id for t in trace)
    assert len(set(ids)) == len(ids)


# -- the drill: promote and rollback under live traffic -----------------


def test_rollout_promotes_with_zero_drop_or_dup(params, candidate,
                                                devices):
    server = LMServer(params, n_slots=2, window=4, **_kw())
    tr = _trace(24)
    res, ctl = run_with_rollout(server, tr, candidate,
                                canary_fraction=0.5, canary_requests=3)
    _assert_one_result_each(res, tr)
    assert all(r.status == "ok" for r in res)
    assert ctl.stage == "promoted"
    assert len(ctl._canary_done) >= 3
    s = server.summary()
    assert s["serve_rollout_stage"] == "promoted"
    assert s["serve_rollout_outcome"] == "promoted"
    assert s["serve_rollouts"] == 1

    # post-promote the LIVE server speaks the candidate weights:
    # bit-identical to a server BUILT on them
    probe = _trace(3, start=100, seed=11)
    want = {r.id: r.tokens for r in
            LMServer(candidate, n_slots=2, window=4,
                     **_kw()).run(probe)}
    got = {r.id: r.tokens for r in server.run(probe)}
    assert got == want


def test_nan_candidate_rolls_back_at_staging(params, devices):
    """The forced-bad drill: staging's spot-check on the compiled
    programs catches NaN weights — no canary ever opens, no client
    request errors, the stage lands rolled_back."""
    server = LMServer(params, n_slots=2, window=4, **_kw())
    bad = jax.tree.map(lambda a: jnp.full_like(a, jnp.nan), params)
    tr = _trace(12)
    res, ctl = run_with_rollout(server, tr, bad, canary_fraction=0.5,
                                canary_requests=3)
    assert ctl.stage == "rolled_back"
    assert "spot-check" in ctl.reason and "non-finite" in ctl.reason
    assert ctl.canary is None
    _assert_one_result_each(res, tr)
    assert all(r.status == "ok" for r in res)
    assert server.summary()["serve_rollout_outcome"] == "rolled_back"

    # live output is untouched by the refused candidate
    probe = _trace(2, start=200, seed=13)
    fresh = {r.id: r.tokens for r in
             LMServer(params, n_slots=2, window=4,
                      **_kw()).run(probe)}
    assert {r.id: r.tokens for r in server.run(probe)} == fresh


def test_insufficient_canary_evidence_rolls_back(params, candidate,
                                                 devices):
    server = LMServer(params, n_slots=2, window=4, **_kw())
    tr = _trace(6)
    res, ctl = run_with_rollout(server, tr, candidate,
                                canary_fraction=0.01,
                                canary_requests=50)
    assert ctl.stage == "rolled_back"
    assert "not enough evidence" in ctl.reason
    _assert_one_result_each(res, tr)
    assert all(r.status == "ok" for r in res)


def test_rollout_from_sharded_checkpoint_path(params, candidate,
                                              devices, tmp_path):
    """The subsystems compose: the candidate arrives as a sharded
    checkpoint DIRECTORY and the controller restores it before
    staging."""
    save_sharded(tmp_path / "cand", candidate)
    server = LMServer(params, n_slots=2, window=4, **_kw())
    tr = _trace(20)
    res, ctl = run_with_rollout(server, tr, str(tmp_path / "cand"),
                                canary_fraction=0.5, canary_requests=2)
    assert ctl.stage == "promoted"
    _assert_one_result_each(res, tr)
    probe = _trace(2, start=300, seed=17)
    want = {r.id: r.tokens for r in
            LMServer(candidate, n_slots=2, window=4,
                     **_kw()).run(probe)}
    assert {r.id: r.tokens for r in server.run(probe)} == want


def test_tenant_affine_routing_is_deterministic(params, candidate,
                                                devices):
    """A tenant's requests all land on ONE side of the split (PR 14
    affinity: prefix locality and quota accounting never straddle)."""
    reg = TenantRegistry()
    for name in ("acme", "globex", "initech", "umbrella"):
        reg.register(name)
    server = LMServer(params, n_slots=2, window=4, tenancy=reg,
                      **_kw())
    ctl = RolloutController(server, candidate, canary_fraction=0.5)
    assert ctl.start()
    sides = {}
    for name in ("acme", "globex", "initech", "umbrella"):
        routed = {ctl.routes_to_canary(
            Request(id=f"q{name}{i}", prompt=(1, 2),
                    max_new_tokens=2, tenant=name)) for i in range(8)}
        assert len(routed) == 1     # whole tenant on one side
        sides[name] = routed.pop()
    assert len(set(sides.values())) == 2    # the split actually splits
    ctl._rollback("test over")


# -- swap primitives ----------------------------------------------------


def test_swap_params_is_zero_recompile_and_validates(params, candidate,
                                                     devices):
    server = LMServer(params, n_slots=2, window=4, **_kw())
    server.run(_trace(2, seed=23))
    sizes = server.engine.cache_sizes()
    server.swap_params(candidate)
    server.run(_trace(2, start=50, seed=29))
    assert server.engine.cache_sizes() == sizes

    with pytest.raises(ValueError, match="not architectures"):
        server.swap_params({"wrong": np.zeros((2, 2), np.float32)})
    grown = jax.tree.map(
        lambda a: np.zeros(tuple(d + 1 for d in a.shape),
                           np.asarray(a).dtype), params)
    with pytest.raises(ValueError, match="not architectures"):
        server.swap_params(grown)


def test_controller_is_single_use(params, candidate, devices):
    server = LMServer(params, n_slots=2, window=4, **_kw())
    ctl = RolloutController(server, candidate, canary_requests=1)
    assert ctl.start()
    with pytest.raises(RolloutError, match="ONE rollout"):
        ctl.start()
    ctl._rollback("test over")
    with pytest.raises(RolloutError, match="ONE rollout"):
        ctl.start()
    with pytest.raises(ValueError, match="canary_fraction"):
        RolloutController(server, candidate, canary_fraction=1.5)
    with pytest.raises(ValueError, match="canary_requests"):
        RolloutController(server, candidate, canary_requests=0)


def test_adapter_hot_swap_first_rung(params, devices):
    """swap_adapters on a live multi-tenant server matches a server
    BUILT with the new bank; a tenant-less server teaches instead."""
    rank = 3
    rng = np.random.default_rng(31)

    def adapter(seed, scale=0.5):
        r = np.random.default_rng(seed)
        return (r.normal(0, scale, (VOCAB, rank)).astype(np.float32),
                r.normal(0, scale, (rank, VOCAB)).astype(np.float32))

    def registry(a, b):
        reg = TenantRegistry()
        reg.register("acme", adapter=a)
        reg.register("globex", adapter=b)
        return reg

    a0, b0 = adapter(1), adapter(2)
    a1, b1 = adapter(3), adapter(4)
    live = LMServer(params, n_slots=2, window=4,
                    tenancy=registry(a0, b0), **_kw())
    probe = _trace(4, tenant="acme", seed=37)
    live.run(probe)

    # build the new bank rows in registry order and hot-swap
    u = np.stack([a1[0], b1[0]])
    v = np.stack([a1[1], b1[1]])
    live.swap_adapters(u, v)
    probe2 = _trace(4, start=60, tenant="acme", seed=41)
    want = {r.id: r.tokens for r in
            LMServer(params, n_slots=2, window=4,
                     tenancy=registry(a1, b1), **_kw()).run(probe2)}
    got = {r.id: r.tokens for r in live.run(probe2)}
    assert got == want

    bare = LMServer(params, n_slots=2, window=4, **_kw())
    with pytest.raises(ValueError, match="multi-tenant"):
        bare.swap_adapters(u, v)
    with pytest.raises(ValueError, match="armed bank"):
        live.swap_adapters(u[:, :, :2], v[:, :2, :])


def test_quiesce_collects_without_dispatch(params, devices):
    """Scheduler.quiesce: one cycle that collects the in-flight window
    without dispatching another — afterwards the engine is
    dispatch-idle (the paged spot-check precondition) and the pending
    requests still finish on later ticks."""
    server = LMServer(params, n_slots=2, window=4, **_kw())
    for _, r in _trace(3, seed=43):
        server.submit(r)
    server.step()
    server.quiesce()
    assert server.engine._pending is None
    done = server.drain()
    assert server.scheduler.idle()
    assert all(r.status == "ok" for r in server.results())


# -- fleet scale --------------------------------------------------------


def _fleet(params, n=2, **kw):
    reps = [build_replica(params, replica_id=f"rep{i}", n_slots=2,
                          window=4, **_kw(), **kw) for i in range(n)]
    return reps, Router(reps)


def test_router_rollout_promotes_fleet(params, candidate, devices):
    reps, router = _fleet(params)
    router.run(_trace(6, seed=47))
    canary_id = router.start_rollout(candidate)
    assert canary_id in {"rep0", "rep1"}
    router.run(_trace(6, start=70, seed=53))
    assert router.finish_rollout() == "promoted"
    # EVERY replica now speaks the candidate weights
    probe = _trace(2, start=400, seed=59)
    want = [r.tokens for r in sorted(
        LMServer(candidate, n_slots=2, window=4, **_kw()).run(probe),
        key=lambda r: r.id)]
    for rep in reps:
        renamed = [(t, Request(id=f"{q.id}-{rep.replica_id}",
                               prompt=q.prompt,
                               max_new_tokens=q.max_new_tokens))
                   for t, q in probe]
        got = [r.tokens for r in sorted(rep.server.run(renamed),
                                        key=lambda r: r.id)]
        assert got == want, rep.replica_id


def test_router_rollout_nan_refused_fleet_untouched(params, devices):
    _, router = _fleet(params)
    bad = jax.tree.map(lambda a: jnp.full_like(a, jnp.nan), params)
    with pytest.raises(ValueError, match="spot-check"):
        router.start_rollout(bad)
    assert router._rollout is None
    res = router.run(_trace(4, seed=61))
    assert all(r.status == "ok" for r in res)


def test_router_rollout_api_misuse_teaches(params, candidate, devices):
    _, router = _fleet(params)
    with pytest.raises(RuntimeError, match="no rollout open"):
        router.finish_rollout()
    router.start_rollout(candidate, replica_id="rep1")
    with pytest.raises(RuntimeError, match="already open"):
        router.start_rollout(candidate)
    assert router.finish_rollout() == "promoted"
    with pytest.raises(ValueError, match="decode-capable"):
        router.replicas[0].drain()
        router.start_rollout(candidate, replica_id="rep0")
