"""Runtime probes for the two ENVIRONMENTAL tier-1 failures on this
container (ISSUE 7 satellite, the ISSUE-4 `_layout_probe` pattern):
each test that fails for a pinned below-the-framework reason gets a
minimal discriminating reproducer run once per session — the test
SKIPS here with the documented root cause, and runs for real on
backends where the capability/contract holds. Both failures were
A/B-verified pre-existing on the unmodified pre-PR tree (git stash,
twice — see CHANGES.md PR 4).

1. `multiprocess_cpu_ok` — test_multihost::test_two_process_dp_step_agrees.
   This container's jaxlib XLA:CPU backend does not implement
   multiprocess computations at all: the FIRST cross-process dispatch
   (any psum over a 2-process mesh) raises
   ``XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
   aren't implemented on the CPU backend.`` — a backend capability
   gap, nothing the framework's collectives can route around. The
   probe runs exactly that minimal program (2 OS processes x 1 virtual
   device, one cross-process psum) and skips ONLY on the documented
   error string; any other failure lets the real test run and surface
   it.

2. `vgg_surrogate_head_learns` — test_golden_learning::
   test_vgg16_two_phase_learns_task_from_pretrained. The test starts
   VGG16 from a deterministic center-tap channel-averaging surrogate
   backbone (no ImageNet artifact in this no-egress environment).
   Those kernels average their input channels, so by the last conv
   block all 512 GAP feature channels are IDENTICAL per example — the
   512-weight logistic head collapses to one effective degree of
   freedom on a scalar brightness feature. Measured on this container:
   images land in [0, 0.9], init logits sit at 0.54 +/- ~0.15 (the
   whole usable signal band), and any coherent optimizer step through
   512 identical channels moves the logit by ~lr x 512 x feature — more
   than the band — so phase-1 head training OSCILLATES at chance
   (loss 0.62<->0.68 over entire epochs, RMSprop and SGD alike) where
   the pinned trajectory on the seed backend descended to 0.932. The
   probe re-runs that mechanism in miniature (the frozen surrogate
   features of a small batch + the same Keras-form RMSprop head
   training) and skips only when the head provably fails to descend.
"""

from __future__ import annotations

import functools
import subprocess
import sys
from pathlib import Path

MULTIPROC_ERR = "Multiprocess computations aren't implemented"

_PROBE_WORKER = r"""
import sys
coordinator, n, i = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
repo = sys.argv[4]
sys.path.insert(0, repo)
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from idc_models_tpu import mesh as meshlib
meshlib.force_host_devices(1)
import jax
jax.config.update("jax_platforms", "cpu")
meshlib.initialize_multihost(coordinator=coordinator, num_processes=n,
                             process_id=i)
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from idc_models_tpu.compat import shard_map
mesh = meshlib.data_mesh()          # spans BOTH processes (2 devices)
f = jax.jit(shard_map(lambda x: jax.lax.psum(x, meshlib.DATA_AXIS),
                      mesh=mesh, in_specs=P(meshlib.DATA_AXIS),
                      out_specs=P(), check_vma=False))
out = f(jnp.arange(n, dtype=jnp.float32))
print("PROBE_SUM", float(jax.device_get(out)))
"""


@functools.lru_cache(maxsize=1)
def multiprocess_cpu_ok() -> bool:
    """Can THIS jax/jaxlib run a cross-process collective on CPU? Two
    1-device processes psum over a 2-process mesh; False only on the
    documented XLA:CPU capability error."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    repo = str(Path(__file__).resolve().parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_WORKER, coordinator, "2",
             str(i), repo],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            # a hung probe is NOT the documented failure — run the real
            # test and let it report whatever is actually wrong
            return True
        outs.append(out)
    if any(MULTIPROC_ERR in out for out in outs):
        return False
    return True


MULTIPROC_SKIP_REASON = (
    "this jaxlib's XLA:CPU backend cannot run multiprocess "
    "computations (first cross-process psum raises INVALID_ARGUMENT: "
    "'Multiprocess computations aren't implemented on the CPU "
    "backend' — probed by tests/_env_probes.py; failed identically on "
    "the unmodified pre-PR tree, root-caused in PR 7): the 2-process "
    "DCN stand-in is unrunnable here and runs for real on backends "
    "with multiprocess support (TPU pods, newer XLA:CPU)")


@functools.lru_cache(maxsize=1)
def vgg_surrogate_head_learns() -> bool:
    """Does phase-1 head-only training DESCEND on the center-tap
    surrogate's collapsed GAP features here? The discriminating
    mechanism in miniature: freeze the surrogate backbone, extract the
    GAP features of one small batch, train the 512->1 head with the
    same Keras-form RMSprop the two-phase fit uses, and check the loss
    actually falls below its starting band. On the seed backend this
    descends (the full test measured 0.932 accuracy); here it
    oscillates at chance."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from idc_models_tpu.data import synthetic
    from idc_models_tpu.models.vgg import vgg16, vgg16_backbone
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    backbone = vgg16_backbone(3)
    bvars = backbone.init(jax.random.key(0))
    shapes = jax.eval_shape(lambda: dict(p=bvars.params))["p"]
    bb = {}
    for layer, leaves in shapes.items():
        kh, kw, cin, cout = leaves["kernel"].shape
        k = np.zeros((kh, kw, cin, cout), np.float32)
        k[1, 1, :, :] = 1.0 / cin       # the test's exact surrogate
        bb[layer] = {"kernel": jnp.asarray(k),
                     "bias": jnp.zeros((cout,), jnp.float32)}

    imgs, labels = synthetic.make_idc_like(64, size=50, seed=3)
    x = jnp.asarray(imgs, jnp.float32)
    y = jnp.asarray(labels, jnp.float32)

    # the frozen-backbone GAP features, computed ONCE with params as
    # ARGUMENTS (closing over them would make XLA constant-fold the
    # whole VGG forward at compile time — minutes of constant folding
    # for a probe): exactly the tensor phase 1's head sees
    @jax.jit
    def feats_of(p, xi):
        fm, _ = backbone.apply(p, bvars.state, xi, train=False)
        return fm.mean(axis=(1, 2))

    feats = feats_of(bb, x)                       # [B, 512]
    head = vgg16(1).init(jax.random.key(0)).params["head"]
    opt = rmsprop(1e-3)
    opt_state = opt.init(head)

    def loss_of(hp):
        logits = (feats @ hp["kernel"] + hp["bias"]).reshape(-1)
        return binary_cross_entropy(logits.astype(jnp.float32), y)

    @jax.jit
    def step(hp, os_):
        loss, g = jax.value_and_grad(loss_of)(hp)
        updates, os_ = opt.update(g, os_, hp)
        return optax.apply_updates(hp, updates), os_, loss

    losses = []
    for _ in range(24):
        head, opt_state, loss = step(head, opt_state)
        losses.append(float(loss))
    # descent = the best late loss sits clearly below the starting
    # band; the pathological backend oscillates inside it instead
    start = float(np.mean(losses[:4]))
    end = float(np.min(losses[-6:]))
    return end < start - 0.05


VGG_SURROGATE_SKIP_REASON = (
    "the center-tap channel-averaging surrogate collapses all 512 GAP "
    "channels to one scalar brightness feature, and on this backend "
    "the head's RMSprop training oscillates at chance inside the "
    "~0.15-wide init logit band instead of descending (probed by "
    "tests/_env_probes.py: 24 head-only steps on the frozen surrogate "
    "features never leave the starting loss band; failed identically "
    "on the unmodified pre-PR tree, root-caused in PR 7) — the 0.9 "
    "accuracy bar is unreachable here and the test runs for real on "
    "backends where the head descends (the seed backend measured "
    "0.932)")
