"""Ring attention == full attention, values AND gradients, any ring size.

The sequence axis is sharded over the virtual 8-device mesh; the ring
result must match single-device full attention to fp tolerance — exact
attention, not an approximation — and `jax.grad` must flow through the
`ppermute` ring unchanged (the property that makes it usable in
training, not just inference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_attention import (
    full_attention, make_ring_attention, ring_attention,
)

B, T, H, D = 2, 32, 2, 8


def _qkv(seed=0, dtype=jnp.float32, b=B):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, T, H, D)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [8, 4, 1])
def test_matches_full_attention(devices, causal, n_dev):
    q, k, v = _qkv()
    mesh = meshlib.seq_mesh(n_dev)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_full_attention(devices, causal):
    q, k, v = _qkv(seed=3)
    mesh = meshlib.seq_mesh(8)
    ring = make_ring_attention(mesh, causal=causal)

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring(q, k, v)))

    def full_loss(q, k, v):
        return jnp.sum(jnp.square(full_attention(q, k, v, causal=causal)))

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        assert bool(jnp.all(jnp.isfinite(gr))), name
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


def test_bf16_inputs(devices):
    q, k, v = _qkv(seed=5, dtype=jnp.bfloat16)
    mesh = meshlib.seq_mesh(8)
    out = ring_attention(q, k, v, mesh, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.05)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
def test_2d_data_seq_mesh(devices, causal, shape):
    """DP x SP composition: on a ("data", "seq") mesh the batch shards
    over "data" while each data row runs its own ring — results must
    equal full attention for every batch element."""
    n_data, n_seq = shape
    q, k, v = _qkv(seed=21, b=4)
    mesh = meshlib.data_seq_mesh(n_seq, n_data)
    assert mesh.axis_names == ("data", "seq")
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_2d_mesh_sharded_inputs_no_reshard(devices):
    """Device-resident ("data", "seq")-sharded q/k/v run unchanged and
    the output keeps BOTH shardings."""
    q, k, v = _qkv(seed=23, b=4)
    mesh = meshlib.data_seq_mesh(4, 2)
    sh = meshlib.sharding(mesh, "data", "seq")
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=True)
    assert out.sharding.spec[0] == ("data",) or \
        out.sharding.spec[0] == "data"
    assert out.sharding.spec[1] == "seq"
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(full_attention(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-5)


def test_2d_mesh_gradients(devices):
    q, k, v = _qkv(seed=25, b=4)
    mesh = meshlib.data_seq_mesh(4, 2)
    ring = make_ring_attention(mesh, causal=True)
    g_ring = jax.grad(lambda a, b, c: jnp.sum(ring(a, b, c) ** 2),
                      (0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda a, b, c: jnp.sum(
        full_attention(a, b, c, causal=True) ** 2), (0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


def test_sharded_inputs_stay_sharded(devices):
    """Device-resident T-sharded inputs run without resharding and the
    output keeps the sequence sharding (the whole point: no device ever
    holds the full sequence)."""
    q, k, v = _qkv(seed=7)
    mesh = meshlib.seq_mesh(8)
    sh = meshlib.sharding(mesh, None, meshlib.SEQ_AXIS)
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh)
    assert out.sharding.spec[1] == meshlib.SEQ_AXIS
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(full_attention(q, k, v)),
                               rtol=1e-5, atol=1e-5)
