"""Worker for the 2-process multi-host (DCN stand-in) test.

Each OS process plays one "host" of a pod: 4 virtual CPU devices each,
joined through `mesh.initialize_multihost` (jax.distributed). The test
driver (test_multihost.py) launches two of these and checks both report
the same post-step parameter digest — i.e. the data-parallel allreduce
really spanned the process boundary.

Usage: python _multihost_worker.py <coordinator> <num_procs> <proc_id>
"""

import sys


def main() -> int:
    coordinator, num_procs, proc_id = (sys.argv[1], int(sys.argv[2]),
                                       int(sys.argv[3]))
    repo = __file__.rsplit("/tests/", 1)[0]
    sys.path.insert(0, repo)

    import os

    os.environ["JAX_PLATFORMS"] = "cpu"

    from idc_models_tpu import mesh as meshlib

    meshlib.force_host_devices(4)

    import jax

    jax.config.update("jax_platforms", "cpu")
    meshlib.initialize_multihost(coordinator=coordinator,
                                 num_processes=num_procs,
                                 process_id=proc_id)
    assert jax.process_count() == num_procs, jax.process_count()
    assert len(jax.devices()) == 4 * num_procs, jax.devices()

    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu.data import synthetic
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import (
        create_train_state, jit_data_parallel, make_train_step, replicate,
        rmsprop, shard_batch,
    )
    from idc_models_tpu.train.losses import binary_cross_entropy

    mesh = meshlib.data_mesh()   # spans BOTH processes (8 devices)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh)
    # identical global batch on every process; device_put slices out each
    # process's addressable shards
    imgs, labels = synthetic.make_idc_like(64, size=10, seed=0)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)
    key = jax.random.key(1)
    for _ in range(3):
        key, sub = jax.random.split(key)
        state, m = step(state, x, y, sub)

    def param_digest(params, dmesh):
        # leaves may be sharded across other processes' devices (the TP
        # case); reduce to a replicated scalar inside jit before fetching
        return float(jax.jit(
            lambda t: jnp.sum(jax.tree.leaves(t)[0].astype(jnp.float32)),
            out_shardings=meshlib.replicated(dmesh))(params))

    loss = float(m["loss"])
    digest = param_digest(state.params, mesh)
    assert np.isfinite(loss)

    # Evaluator across the process boundary: its eval step's logits are
    # batch-sharded over both hosts; the replicated-gather path must make
    # them fetchable so every host computes identical full-set metrics.
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.train import Evaluator

    ev = Evaluator(model, binary_cross_entropy, mesh, batch_size=16,
                   with_auroc=True)
    em = ev(state, ArrayDataset(imgs, labels))
    assert np.isfinite(em["loss"]) and 0.0 <= em["accuracy"] <= 1.0

    # Federated round across the process boundary: 8 clients, one per
    # device spanning both hosts; the round-boundary weighted pmean rides
    # the jax.distributed (DCN stand-in) link.
    from idc_models_tpu.federated import initialize_server, make_fedavg_round

    n_clients = 4 * num_procs
    cmesh = meshlib.client_mesh(n_clients)
    server = replicate(cmesh, initialize_server(model, jax.random.key(0)))
    round_fn = make_fedavg_round(model, opt, binary_cross_entropy, cmesh,
                                 local_epochs=1, batch_size=8)
    csh = meshlib.sharding(cmesh, meshlib.CLIENT_AXIS)
    ci = meshlib.put_with_sharding(
        imgs.reshape(n_clients, -1, *imgs.shape[1:]), csh)
    cl = meshlib.put_with_sharding(labels.reshape(n_clients, -1), csh)
    w = np.full((n_clients,), ci.shape[1], np.float32)
    for r in range(2):
        server, fm = round_fn(server, ci, cl, w,
                              jax.random.fold_in(jax.random.key(5), r))
    fed_loss = float(fm["loss"])
    fed_digest = param_digest(server.params, cmesh)

    # Secure-aggregation round across processes: pairwise masks are
    # generated per-device from the global client index, and the masked
    # psum must cancel them across the DCN boundary exactly.
    from idc_models_tpu.secure import make_secure_fedavg_round

    sserver = replicate(cmesh, initialize_server(model, jax.random.key(2)))
    sround = make_secure_fedavg_round(model, opt, binary_cross_entropy,
                                      cmesh, percent=0.5, local_epochs=1,
                                      batch_size=8)
    sserver, sm = sround(sserver, ci, cl, jax.random.key(7))
    sec_loss = float(sm["loss"])
    sec_digest = param_digest(sserver.params, cmesh)

    # DP x TP across processes: weights channel-sharded over a "model"
    # axis that REALLY spans the hosts — the model axis is built
    # OUTERMOST ({model: 2, data: 4}) so with row-major device order
    # each channel pair is (device i, device i+4), one on each process;
    # row-major (data, model) would pair intra-host neighbors and never
    # cross DCN. Same workload as the DP section (same init/data/rng),
    # so its loss must reproduce the DP loss through GSPMD's
    # cross-process channel gathers.
    from idc_models_tpu.train.step import place_state

    tpmesh = meshlib.make_mesh({meshlib.MODEL_AXIS: 2,
                                meshlib.DATA_AXIS: 4})
    assert len({d.process_index for d in
                tpmesh.devices[:, 0]}) == num_procs, tpmesh.devices
    tstate = place_state(tpmesh,
                         create_train_state(model, opt, jax.random.key(0)))
    tstep = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), tpmesh)
    tx, ty = shard_batch(tpmesh, imgs, labels)
    tkey = jax.random.key(1)
    for _ in range(3):
        tkey, sub = jax.random.split(tkey)
        tstate, tm = tstep(tstate, tx, ty, sub)
    tp_digest = param_digest(tstate.params, tpmesh)
    tp_loss = float(tm["loss"])

    # Ring attention across processes: the "seq" ring spans both hosts,
    # so the per-step K/V ppermute hop between device 3 and device 4
    # rides the DCN stand-in link; the result must equal single-device
    # full attention computed from the (identical) host copies.
    from idc_models_tpu.ring_attention import (
        full_attention, make_ring_attention,
    )

    rng_sp = np.random.default_rng(11)
    sq, sk, sv = (jnp.asarray(rng_sp.normal(0, 1, (2, 32, 2, 8)),
                              jnp.float32) for _ in range(3))
    smesh = meshlib.seq_mesh()
    ssh = meshlib.sharding(smesh, None, meshlib.SEQ_AXIS)
    qs = meshlib.put_with_sharding(sq, ssh)
    ks = meshlib.put_with_sharding(sk, ssh)
    vs = meshlib.put_with_sharding(sv, ssh)
    sp_out = make_ring_attention(smesh, causal=True)(qs, ks, vs)
    sp_digest = float(jax.jit(
        lambda t: jnp.sum(t.astype(jnp.float32)),
        out_shardings=meshlib.replicated(smesh))(sp_out))
    ref_digest = float(jnp.sum(full_attention(sq, sk, sv, causal=True)))
    assert abs(sp_digest - ref_digest) < 1e-3, (sp_digest, ref_digest)

    # ... the zigzag layout's balanced schedule over the same
    # cross-process ring (its hop pattern differs: half-blocks are
    # selected per step, and the dk/dv trailing hop crosses the
    # boundary on the backward), including gradients THROUGH the ring:
    # grads must match full-attention autodiff computed from host copies.
    from idc_models_tpu.ring_attention import from_zigzag, to_zigzag

    n_ring = smesh.shape[meshlib.SEQ_AXIS]
    zring = make_ring_attention(smesh, causal=True, layout="zigzag")

    def zz_loss(q, k, v):
        zz = [to_zigzag(x, n_ring) for x in (q, k, v)]
        return jnp.sum(jnp.square(from_zigzag(zring(*zz), n_ring)
                                  .astype(jnp.float32)))

    # (grad must run under jit: eager ops on non-fully-addressable
    # arrays are rejected outside the global-semantics program)
    gq = jax.jit(jax.grad(zz_loss),
                 out_shardings=meshlib.replicated(smesh))(qs, ks, vs)
    zz_grad_digest = float(jnp.sum(jax.device_get(gq)))
    gq_ref = jax.grad(lambda q, k, v: jnp.sum(jnp.square(
        full_attention(q, k, v, causal=True))))(sq, sk, sv)
    assert abs(zz_grad_digest - float(jnp.sum(gq_ref))) < 1e-3, (
        zz_grad_digest, float(jnp.sum(gq_ref)))

    # ... and the PALLAS path's backward ring (the custom_vjp whose
    # dk/dv accumulators ride the ppermute hops home) with the k-grad,
    # so a riding accumulator itself crosses the process boundary.
    # T=1024 over 8 devices = the kernel's 128 tile; interpret mode on
    # the CPU devices.
    rng_pl = np.random.default_rng(13)
    pq, pk, pv = (jnp.asarray(rng_pl.normal(0, 1, (1, 1024, 2, 32)),
                              jnp.float32) for _ in range(3))
    pqs, pks, pvs = (meshlib.put_with_sharding(x, ssh)
                     for x in (pq, pk, pv))
    pring = make_ring_attention(smesh, causal=True, block_impl="pallas")
    gk = jax.jit(jax.grad(lambda q, k, v: jnp.sum(jnp.square(
        pring(q, k, v).astype(jnp.float32))), argnums=1),
        out_shardings=meshlib.replicated(smesh))(pqs, pks, pvs)
    flash_bwd_digest = float(jnp.sum(jax.device_get(gk)))
    gk_ref = jax.grad(lambda q, k, v: jnp.sum(jnp.square(
        full_attention(q, k, v, causal=True))), argnums=1)(pq, pk, pv)
    assert abs(flash_bwd_digest - float(jnp.sum(gk_ref))) < 1e-2, (
        flash_bwd_digest, float(jnp.sum(gk_ref)))

    # KV-cache decode across processes: the cache is sharded over the
    # same cross-host "seq" mesh (each host owns half the slots), so the
    # owner-shard appends land on whichever host owns the position and
    # the pmax/psum softmax merge spans the DCN boundary every token.
    from idc_models_tpu.ring_decode import init_cache, make_ring_decode

    t_dec = 16
    kc, vc = init_cache(smesh, 2, t_dec, 2, 8, dtype=jnp.float32)
    dstep = make_ring_decode(smesh)
    repl = meshlib.replicated(smesh)
    drows = []
    for pos in range(t_dec):
        tok = slice(pos, pos + 1)
        q1, k1, v1 = (meshlib.put_with_sharding(np.asarray(x[:, tok]),
                                                repl)
                      for x in (sq, sk, sv))
        drow, kc, vc = dstep(kc, vc, q1, k1, v1, pos)
        drows.append(drow)
    dec = jnp.concatenate([jax.device_get(r) for r in drows], axis=1)
    dec_ref = full_attention(sq[:, :t_dec], sk[:, :t_dec], sv[:, :t_dec],
                             causal=True)
    decode_digest = float(jnp.sum(dec.astype(jnp.float32)))
    assert abs(decode_digest
               - float(jnp.sum(dec_ref.astype(jnp.float32)))) < 1e-3, (
        "cross-process KV-cache decode != full causal attention")

    # Checkpointed fit across processes: orbax save is a collective, so
    # this hangs (not just fails) if any process skips it. The dir is
    # shared (same host in this stand-in, like GCS/NFS on a real pod).
    import os as _os

    from idc_models_tpu.data.idc import ArrayDataset as _ADS
    from idc_models_tpu.train import fit

    ckpt_dir = _os.environ["GRAFT_TEST_CKPT_DIR"]
    opt_c = rmsprop(1e-3)
    state_c = create_train_state(model, opt_c, jax.random.key(0))
    state_c, hist_c = fit(model, opt_c, binary_cross_entropy, state_c,
                          _ADS(imgs, labels), None, mesh, epochs=1,
                          batch_size=16, verbose=False,
                          checkpoint_dir=ckpt_dir)
    assert _os.path.exists(_os.path.join(ckpt_dir, "meta.json"))
    ckpt_loss = float(hist_c["loss"][-1])

    print(f"RESULT proc={proc_id} loss={loss:.8f} digest={digest:.8f} "
          f"eval_loss={em['loss']:.8f} eval_auroc={em['auroc']:.8f} "
          f"fed_loss={fed_loss:.8f} fed_digest={fed_digest:.8f} "
          f"sec_loss={sec_loss:.8f} sec_digest={sec_digest:.8f} "
          f"ckpt_loss={ckpt_loss:.8f} tp_loss={tp_loss:.8f} "
          f"tp_digest={tp_digest:.8f} sp_digest={sp_digest:.8f} "
          f"decode_digest={decode_digest:.8f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
