"""Speculative decoding (ISSUE 10) against its contracts:

1. TOKEN PARITY — greedy speculative output is bit-identical to the
   serial `Generator`, at EVERY accepted-prefix length (0, 1, k-1, k,
   driven by a scripted drafter), across slot recycling, with int8 KV
   caches, with chunked-prefill admission interleaved in the same
   cycle, and under seeded top-k sampling (the verify consumes the
   request's key chain exactly as the fused window would).
2. DRAFTS ARE UNTRUSTED — any `propose` output is sound: the verify
   accepts only what the model itself would have emitted, so garbage
   drafts cost acceptance rate, never correctness.
3. ZERO RECOMPILATION — the verify program is ONE fixed-k executable;
   varying draft-hit patterns and prompt lengths compile nothing after
   warmup (gated here and in tests/test_serve.py).

Plus the n-gram prompt-lookup drafter's host-side semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.draft import NGramDrafter
from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import LMServer, Request, SlotEngine

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw(mesh=None):
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)


def _serial_tokens(gen, prompt, steps, *, rng=None):
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps, rng=rng)
    return toks.tolist()[0]


class ScriptedDrafter:
    """Test drafter forcing an EXACT accepted-prefix length per
    request: the serial oracle's true continuation for the first
    `accept` positions, then guaranteed-wrong tokens (true + 1 mod
    vocab — never equal to the model's own pick). Requests are keyed
    by prompt prefix, so plans need prefix-distinct prompts."""

    def __init__(self, k, plans):
        self.k = k
        self.plans = plans          # [(prompt tuple, stream, accept)]

    def propose(self, history):
        h = [int(x) for x in history]
        for prompt, stream, accept in self.plans:
            p = list(prompt)
            if len(h) < len(p) or h[:len(p)] != p:
                continue
            done = len(h) - len(p)
            cont = list(stream[done:done + self.k])
            cont += [0] * (self.k - len(cont))
            for j in range(accept, self.k):
                cont[j] = (cont[j] + 1) % VOCAB
            return np.asarray(cont, np.int32)
        return None


# -- the drafter ----------------------------------------------------------


def test_ngram_drafter_lookup_and_fallback():
    d = NGramDrafter(3, order=2)
    # trailing (2, 3) recurred: propose what followed it (4, 5, 6)
    got = d.propose([1, 2, 3, 4, 5, 6, 2, 3])
    assert got.tolist() == [4, 5, 6]
    # the MOST RECENT occurrence wins when the n-gram recurs twice
    got = d.propose([2, 3, 7, 2, 3, 9, 1, 2, 3])
    assert got.tolist()[0] == 9
    # order falls back: (5, 1) never recurs but 1 does (order 2 -> 1)
    got = d.propose([1, 8, 4, 5, 1])
    assert got.tolist() == [8, 4, 5]
    # continuation shorter than k pads with the final history token
    got = NGramDrafter(4, order=1).propose([7, 3, 7])
    assert got.tolist() == [3, 7, 7, 7]
    # nothing recurs -> None (fall back to the plain window)
    assert d.propose([1, 2, 3, 4, 5]) is None
    assert d.propose([4]) is None and d.propose([]) is None
    # min_order bounds the fallback
    assert NGramDrafter(2, order=3, min_order=2).propose(
        [1, 8, 4, 5, 1]) is None


def test_ngram_drafter_lookback_bounds_the_scan():
    """The critical-path bound: only the last `lookback` tokens are
    scanned, so a match reachable only in deep history is (cheaply)
    missed, while recent matches still hit — and the default stays
    O(lookback) however long the stream grows."""
    d = NGramDrafter(2, order=2, lookback=6)
    long_hist = [7, 8, 9, 9, 9] * 40 + [1, 2, 3, 4, 1, 2]
    assert d.propose(long_hist).tolist() == [3, 4]   # inside lookback
    # the (7, 8) match exists only beyond the lookback window -> None
    assert d.propose([7, 8, 5] + [0, 6] * 10 + [7, 8]) is None
    assert NGramDrafter(2, order=2, lookback=None).propose(
        [7, 8, 5] + [0, 6] * 10 + [7, 8]).tolist() == [5, 0]


def test_ngram_drafter_validation():
    with pytest.raises(ValueError, match="k >= 1"):
        NGramDrafter(0)
    with pytest.raises(ValueError, match="min_order"):
        NGramDrafter(2, order=2, min_order=3)
    with pytest.raises(ValueError, match="min_order"):
        NGramDrafter(2, order=2, min_order=0)
    with pytest.raises(ValueError, match="lookback"):
        NGramDrafter(2, order=3, lookback=2)


# -- accept-length boundary parity ---------------------------------------


def test_parity_at_every_accept_length(devices, params):
    """Accepted-prefix lengths 0, 1, k-1, and k (scripted drafter) all
    emit streams bit-identical to the serial Generator — the verify's
    budget/bonus/logits bookkeeping is exact at every boundary."""
    k = 4
    gen = Generator(params, **_kw())
    prompts = [(i, 2 + i % 3, 5) for i in range(4)]   # prefix-distinct
    budgets = [11, 12, 13, 9]
    accepts = [0, 1, k - 1, k]
    streams = [_serial_tokens(gen, p, b)
               for p, b in zip(prompts, budgets)]
    drafter = ScriptedDrafter(
        k, [(p, s, a) for p, s, a in zip(prompts, streams, accepts)])
    server = LMServer(params, n_slots=4, window=4, spec_decode=True,
                      draft_k=k, drafter=drafter, **_kw())
    reqs = [Request(id=f"a{i}", prompt=p, max_new_tokens=b)
            for i, (p, b) in enumerate(zip(prompts, budgets))]
    server.run([(0.0, r) for r in reqs])
    for r, s in zip(reqs, streams):
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        assert got.tokens == s, (r.id, got.tokens, s)
    summary = server.summary()
    assert summary["serve_spec_verify_dispatches"] > 0
    assert summary["serve_spec_accepted"] > 0
    # the full-accept request advanced k+1 tokens on some verify; the
    # zero-accept one advanced exactly 1 per verify — both are inside
    # the per-slot tokens-per-dispatch mean
    assert summary["serve_spec_tokens_per_dispatch"] >= 1.0


def test_parity_with_eos_inside_accepted_prefix(devices, params):
    """An EOS hit INSIDE the accepted draft prefix truncates exactly
    like the fused window's device rule: emitted through the EOS
    (inclusive), budget zeroed, the stream equal to the serial one cut
    at its first EOS."""
    k = 4
    gen = Generator(params, **_kw())
    prompt = (1, 2, 3)
    stream = _serial_tokens(gen, prompt, 12)
    eos = stream[5]                       # lands mid-draft at k=4
    cut = stream[:stream.index(eos) + 1]
    drafter = ScriptedDrafter(k, [(prompt, stream, k)])  # full accept
    server = LMServer(params, n_slots=1, window=4, eos_id=eos,
                      spec_decode=True, draft_k=k, drafter=drafter,
                      **_kw())
    server.run([(0.0, Request(id="e", prompt=prompt,
                              max_new_tokens=12))])
    got = server.poll("e")
    assert got.finish_reason == "eos" and got.tokens == cut


def test_parity_across_slot_recycle_and_budget_edges(devices, params):
    """Speculative traffic with slot recycling AND a request whose
    prompt + budget fills the cache to t_max exactly: near the edge
    `spec_room` fails and the policy falls back to plain windows, so
    the request still finishes — all streams bit-identical to
    serial."""
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(23)
    reqs, plans = [], []
    for i in range(6):
        p = tuple(int(x) for x in rng.integers(0, VOCAB, 3 + 2 * i))
        b = SEQ - len(p) if i == 2 else 4 + (i % 4) * 3
        reqs.append(Request(id=f"r{i}", prompt=p, max_new_tokens=b))
        plans.append((p, _serial_tokens(gen, p, b), 4))
    drafter = ScriptedDrafter(4, plans)
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=4, drafter=drafter, **_kw())
    server.run([(0.0, r) for r in reqs])
    for r, (_, s, _) in zip(reqs, plans):
        got = server.poll(r.id)
        assert got.status == "ok" and got.tokens == s, r.id


def test_sampled_parity_with_speculation(devices, params):
    """Seeded top-k sampling THROUGH the verify program: the accept
    rule samples along the request's exact serial key chain (one split
    per emitted token), so speculative streams match serial seeded
    decode bit-for-bit — accepted drafts, bonus picks, and the key
    handed to the next window alike."""
    k = 3
    gen = Generator(params, temperature=1.3, top_k=4, **_kw())
    prompts = [(i, 9 - i, 1, 4) for i in range(3)]
    seeds = [100 + i for i in range(3)]
    budgets = [8, 10, 7]
    streams = [_serial_tokens(gen, p, b, rng=jax.random.key(s))
               for p, b, s in zip(prompts, budgets, seeds)]
    # mixed accept lengths, incl. full accept of SAMPLED continuations
    drafter = ScriptedDrafter(
        k, [(p, s, a) for p, s, a
            in zip(prompts, streams, (k, 1, 0))])
    server = LMServer(params, n_slots=3, window=4, temperature=1.3,
                      top_k=4, spec_decode=True, draft_k=k,
                      drafter=drafter, **_kw())
    reqs = [Request(id=f"s{i}", prompt=p, max_new_tokens=b, seed=s)
            for i, (p, b, s) in enumerate(zip(prompts, budgets, seeds))]
    server.run([(0.0, r) for r in reqs])
    for r, s in zip(reqs, streams):
        got = server.poll(r.id)
        assert got.status == "ok" and got.tokens == s, r.id
    assert server.summary()["serve_spec_accepted"] > 0


def test_spec_parity_on_ring_sharded_cache(devices, params):
    """Speculative decode with the KV caches SHARDED over a seq=4
    ring: the batched chunk fold's per-row splice + two-collective
    merge must reproduce the serial ring decode's streams exactly
    (greedy), drafts hitting and missing alike."""
    from idc_models_tpu import mesh as meshlib

    mesh = meshlib.seq_mesh(4)
    gen = Generator(params, **_kw(mesh))
    rng = np.random.default_rng(47)
    prompts = [tuple(int(x) for x in rng.integers(0, VOCAB, 4 + 3 * i))
               for i in range(3)]
    budgets = [7, 9, 6]
    plans = [(p, _serial_tokens(gen, p, b), a)
             for p, b, a in zip(prompts, budgets, (4, 2, 0))]
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=4, drafter=ScriptedDrafter(4, plans),
                      **_kw(mesh))
    reqs = [Request(id=f"g{i}", prompt=p, max_new_tokens=b)
            for i, (p, b) in enumerate(zip(prompts, budgets))]
    server.run([(0.0, r) for r in reqs])
    for r, (_, s, _) in zip(reqs, plans):
        got = server.poll(r.id)
        assert got.status == "ok" and got.tokens == s, r.id
    assert server.summary()["serve_spec_accepted"] > 0


def test_int8_kv_speculative_parity(devices, params):
    """Spec decode over int8 KV caches: the verify's chunk fold
    dequantizes by the same factored per-(slot, head) scales as the
    decode fold, and greedy output still tracks the serial (float)
    path exactly at this scale — the PR-4 drift bound holds through
    speculation."""
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(31)
    prompts = [tuple(int(x) for x in rng.integers(0, VOCAB, 4 + 3 * i))
               for i in range(3)]
    budgets = [6, 8, 7]
    plans = [(p, _serial_tokens(gen, p, b), 4)
             for p, b in zip(prompts, budgets)]
    server = LMServer(params, n_slots=2, window=4, kv_dtype="int8",
                      spec_decode=True, draft_k=4,
                      drafter=ScriptedDrafter(4, plans), **_kw())
    reqs = [Request(id=f"i{i}", prompt=p, max_new_tokens=b)
            for i, (p, b) in enumerate(zip(prompts, budgets))]
    server.run([(0.0, r) for r in reqs])
    for r, (_, s, _) in zip(reqs, plans):
        got = server.poll(r.id)
        assert got.status == "ok" and got.tokens == s, r.id
    assert server.summary()["serve_spec_verify_dispatches"] > 0


def test_spec_with_chunked_prefill_same_cycle(devices, params):
    """A long prompt chunking its way in WHILE other slots run verify
    dispatches — speculative decode and chunked-prefill admission in
    one scheduler cycle — with every stream bit-identical to serial,
    including the chunked request once it starts decoding."""
    gen = Generator(params, **_kw())
    p_run = (1, 2, 3)
    p_long = tuple(int(x) for x in
                   np.random.default_rng(41).integers(0, VOCAB, 17))
    s_run = _serial_tokens(gen, p_run, 16)
    s_long = _serial_tokens(gen, p_long, 6)
    drafter = ScriptedDrafter(4, [(p_run, s_run, 4),
                                  (p_long, s_long, 4)])
    server = LMServer(params, n_slots=2, window=2, prefill_chunk=4,
                      spec_decode=True, draft_k=4, drafter=drafter,
                      **_kw())
    server.submit(Request(id="run", prompt=p_run, max_new_tokens=16))
    server.step()                # admit "run"; it decodes from here
    server.submit(Request(id="long", prompt=p_long, max_new_tokens=6))
    # while "long" chunks (5 chunks of 4), "run" must keep emitting —
    # and with its drafter scripted to full accept, via VERIFY
    # dispatches in the same cycles the chunks step
    before = server.summary()["serve_spec_verify_dispatches"]
    while server.poll("long") is None or server.poll("run") is None:
        server.step()
    assert server.summary()["serve_spec_verify_dispatches"] > before
    assert server.poll("run").tokens == s_run
    assert server.poll("long").tokens == s_long


def test_spec_ledger_counts_only_real_proposals(devices, params):
    """A slot riding along on the scheduler's placeholder drafts (its
    drafter returned None) must not dilute the accept ledger: with one
    full-accept proposer and one silent slot, the accept rate reads
    ~1.0 — not ~0.5 — and every drafted token belongs to the slot that
    actually proposed. Operators tune speculation off below ~1/k
    acceptance, so dilution here would disable it exactly where it
    wins."""
    gen = Generator(params, **_kw())
    p_hit, p_quiet = (1, 2, 3), (4, 5)
    s_hit = _serial_tokens(gen, p_hit, 12)
    s_quiet = _serial_tokens(gen, p_quiet, 12)
    drafter = ScriptedDrafter(4, [(p_hit, s_hit, 4)])  # quiet: None
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=4, drafter=drafter, **_kw())
    server.run([(0.0, Request(id="h", prompt=p_hit, max_new_tokens=12)),
                (0.0, Request(id="q", prompt=p_quiet,
                              max_new_tokens=12))])
    assert server.poll("h").tokens == s_hit
    assert server.poll("q").tokens == s_quiet      # rode along, exact
    s = server.summary()
    assert s["serve_spec_verify_dispatches"] > 0
    # drafted counts ONLY the proposing slot: k per verify dispatch
    assert s["serve_spec_drafted"] == 4 * s["serve_spec_verify_dispatches"]
    assert s["serve_spec_accept_rate"] >= 0.75, s


def test_spec_no_recompile_across_hit_patterns(devices, params):
    """The fixed-k verify program is ONE executable: after the first
    wave, speculative traffic of varying prompt lengths AND varying
    draft-hit patterns (full accept, partial, zero, drafter silence ->
    window fallback) grows no jit cache — the ISSUE-10 compile gate at
    the unit level (the server-level gate lives in test_serve.py)."""
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(57)

    def mk(i, accept, n):
        p = tuple(int(x) for x in rng.integers(0, VOCAB, 3 + i))
        b = 4 + (i % 3) * 3
        return (Request(id=f"{n}{i}", prompt=p, max_new_tokens=b),
                (p, _serial_tokens(gen, p, b), accept))
    wave1 = [mk(i, a, "w") for i, a in enumerate((4, 0))]
    wave2 = [mk(i + 2, a, "x") for i, a in enumerate((1, 3, 4, 0))]
    drafter = ScriptedDrafter(4, [pl for _, pl in wave1 + wave2])
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=4, drafter=drafter, **_kw())
    server.run([(0.0, r) for r, _ in wave1])
    sizes = server.engine.cache_sizes()
    assert "verify" in sizes
    server.run([(0.0, r) for r, _ in wave2])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    for r, (_, s, _) in wave1 + wave2:
        assert server.poll(r.id).tokens == s, r.id


def test_engine_verify_validation(devices, params):
    """The engine-level contracts: draft_k bounds, drafts/vlive shape
    checks, verify on an unarmed engine, and vlive rows that lack
    occupancy or room are refused before any dispatch."""
    with pytest.raises(ValueError, match="draft_k"):
        SlotEngine(params, n_slots=1, draft_k=SEQ, **_kw())
    eng = SlotEngine(params, n_slots=2, **_kw())
    with pytest.raises(RuntimeError, match="without draft_k"):
        eng.begin_verify(np.zeros((2, 4), np.int32),
                         np.zeros(2, bool))
    assert not eng.spec_room(0)          # unarmed: never eligible
    eng = SlotEngine(params, n_slots=2, draft_k=4, **_kw())
    eng.warmup(2)
    with pytest.raises(ValueError, match="drafts must be"):
        eng.begin_verify(np.zeros((2, 3), np.int32), np.zeros(2, bool))
    with pytest.raises(ValueError, match="vlive must be"):
        eng.begin_verify(np.zeros((2, 4), np.int32), np.zeros(3, bool))
    with pytest.raises(ValueError, match="not occupied"):
        eng.begin_verify(np.zeros((2, 4), np.int32),
                         np.ones(2, bool))
    # a slot too close to t_max for k drafts + the bonus is refused
    eng.admit(0, list(range(1, SEQ - 3)), 4)     # pos = SEQ - 4
    assert not eng.spec_room(0)
    vl = np.array([True, False])
    with pytest.raises(ValueError, match="lacks room"):
        eng.begin_verify(np.zeros((2, 4), np.int32), vl)
