"""ISSUE 16 parity suite: fused Pallas backbone paths vs their unfused
references.

Everything here runs the REAL kernel body: on CPU `interpret=None`
resolves to the Pallas interpreter (ops/fused_conv.default_interpret),
which executes the same `_kernel` the TPU lowers through Mosaic — the
tier-1-on-CPU testing contract. Tolerances match the taps-parity suite
(tests/test_core_layers.py): rtol=1e-5 / atol=1e-6 for forward paths
accumulating in f32.
"""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models import core, densenet, mobilenet
from idc_models_tpu.ops import fused_conv

RTOL, ATOL = 1e-5, 1e-6


def _rand(rng, shape, scale=1.0):
    return jnp.asarray(rng.normal(0, scale, shape), jnp.float32)


# ---------------------------------------------------------------------------
# op level: Pallas kernel vs the jnp reference, and vs XLA's grouped conv
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stride,size,c", [
    (1, 8, 6), (2, 7, 6), (2, 25, 32), (1, 25, 96),
])
@pytest.mark.parametrize("clamp6", [True, False])
def test_fused_op_matches_reference(stride, size, c, clamp6):
    rng = np.random.default_rng(0)
    x = _rand(rng, (2, size, size, c))
    w = _rand(rng, (3, 3, 1, c), 0.3)
    mul = _rand(rng, (c,), 0.5) + 1.0
    add = _rand(rng, (c,), 0.5)
    got = fused_conv.fused_depthwise_affine(x, w, mul, add,
                                            stride=stride, clamp6=clamp6)
    want = fused_conv.reference_impl(x, w, mul, add, stride=stride,
                                     clamp6=clamp6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("stride,size", [(1, 8), (2, 7), (2, 25)])
def test_fused_module_matches_grouped(stride, size):
    """core.depthwise_conv2d(impl="fused") (identity affine inside the
    kernel) against XLA's grouped lowering — same contract the taps
    parity test pins."""
    c = 16
    mods = {impl: core.depthwise_conv2d(c, 3, stride=stride,
                                        use_bias=False, impl=impl,
                                        name="dw")
            for impl in ("grouped", "fused")}
    v = mods["grouped"].init(jax.random.key(0))
    x = _rand(np.random.default_rng(1), (2, size, size, c))
    outs = {}
    for impl, m in mods.items():
        outs[impl], _ = m.apply(v.params, v.state, x)
    np.testing.assert_allclose(np.asarray(outs["fused"]),
                               np.asarray(outs["grouped"]),
                               rtol=RTOL, atol=ATOL)


def test_fused_module_rejects_valid_padding():
    with pytest.raises(ValueError, match="SAME"):
        core.depthwise_conv2d(8, 3, impl="fused", padding="VALID")


def test_channel_tile_must_divide():
    rng = np.random.default_rng(0)
    x = _rand(rng, (1, 5, 5, 6))
    w = _rand(rng, (3, 3, 1, 6), 0.3)
    one = jnp.ones((6,), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        fused_conv.fused_depthwise_affine(x, w, one, one * 0,
                                          channel_tile=4)
    # a dividing tile is numerically identical to whole-C
    got = fused_conv.fused_depthwise_affine(x, w, one, one * 0,
                                            channel_tile=2)
    want = fused_conv.fused_depthwise_affine(x, w, one, one * 0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_fused_full_mobilenet_channel_schedule():
    """Every (spatial, channels, stride) the fused chain actually sees
    in MobileNetV2 at the paper's 50x50 patches — the full schedule
    from `fused_call_shapes`, including the odd 25x25 and 13x13 edges."""
    rng = np.random.default_rng(2)
    for call in mobilenet.fused_call_shapes(1, 50):
        c, s = call["c"], call["stride"]
        x = _rand(rng, (1, call["h_in"], call["w_in"], c))
        w = _rand(rng, (3, 3, 1, c), 0.3)
        mul = _rand(rng, (c,), 0.5) + 1.0
        add = _rand(rng, (c,), 0.5)
        got = fused_conv.fused_depthwise_affine(x, w, mul, add, stride=s)
        want = fused_conv.reference_impl(x, w, mul, add, stride=s)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=RTOL, atol=ATOL,
            err_msg=f"schedule entry {call} diverged")


# ---------------------------------------------------------------------------
# model level: MobileNetV2 fused chain vs the grouped composition
# ---------------------------------------------------------------------------


def _mobile_pair(size=25, *, bn_frozen_below=0):
    m_f = mobilenet.mobilenet_v2_backbone(
        3, bn_frozen_below=bn_frozen_below, depthwise_impl="fused")
    m_g = mobilenet.mobilenet_v2_backbone(
        3, bn_frozen_below=bn_frozen_below, depthwise_impl="grouped")
    v = m_f.init(jax.random.key(0))
    x = _rand(np.random.default_rng(3), (2, size, size, 3))
    return m_f, m_g, v, x


def test_mobilenet_eval_fused_matches_grouped():
    m_f, m_g, v, x = _mobile_pair()
    y_f, _ = m_f.apply(v.params, v.state, x, train=False)
    y_g, _ = m_g.apply(v.params, v.state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_g),
                               rtol=1e-4, atol=1e-4)


def test_mobilenet_frozen_train_fused_parity_and_static_state():
    """With every BN frozen the fused chain engages even in train mode;
    outputs must match the grouped composition and the returned state
    must be bitwise-identical to the input (frozen BN never updates —
    the bypass contract unit_backbone's `run` attributes document)."""
    m_f, m_g, v, x = _mobile_pair(bn_frozen_below=mobilenet.FREEZE_ALL)
    y_f, s_f = m_f.apply(v.params, v.state, x, train=True)
    y_g, _ = m_g.apply(v.params, v.state, x, train=True)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_g),
                               rtol=1e-4, atol=1e-4)
    flat_in = jax.tree_util.tree_leaves_with_path(v.state)
    flat_out = dict(jax.tree_util.tree_leaves_with_path(s_f))
    for path, leaf in flat_in:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.asarray(flat_out[path]),
            err_msg=f"frozen-train fused state drifted at {path}")


def test_mobilenet_fused_grad_parity():
    """The custom_vjp backward (jax.vjp of the jnp reference) against
    the grouped path's ordinary autodiff, through the whole backbone."""
    m_f, m_g, v, x = _mobile_pair(size=13,
                                  bn_frozen_below=mobilenet.FREEZE_ALL)

    def loss(m):
        def f(params):
            y, _ = m.apply(params, v.state, x, train=True)
            return jnp.sum(y.astype(jnp.float32) ** 2)
        return f

    g_f = jax.grad(loss(m_f))(v.params)
    g_g = jax.grad(loss(m_g))(v.params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_f),
            jax.tree_util.tree_leaves_with_path(g_g)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-3,
            err_msg=f"grad diverged at {path}")


def test_mobilenet_fused_through_keras_h5(tmp_path):
    """The full pretrained round trip: a Keras-layout h5 whose depthwise
    kernel is stored (kh, kw, C, 1) — exercising load_keras_h5's
    (kh, kw, in, 1) -> (kh, kw, 1, in) swap — merged into fused and
    grouped builds, which must then agree on a forward pass."""
    h5py = pytest.importorskip("h5py")

    rng = np.random.default_rng(4)
    dw_keras = rng.normal(0, 0.3, (3, 3, 32, 1)).astype(np.float32)
    gamma = (rng.normal(0, 0.2, (32,)) + 1.0).astype(np.float32)
    beta = rng.normal(0, 0.2, (32,)).astype(np.float32)
    mean = rng.normal(0, 0.2, (32,)).astype(np.float32)
    var = (rng.random(32) + 0.5).astype(np.float32)
    path = tmp_path / "weights.h5"
    with h5py.File(path, "w") as f:
        g = f.create_group("expanded_conv_depthwise")
        g.attrs["weight_names"] = [
            b"expanded_conv_depthwise/depthwise_kernel:0"]
        g.create_dataset("expanded_conv_depthwise/depthwise_kernel:0",
                         data=dw_keras)
        g = f.create_group("expanded_conv_depthwise_BN")
        g.attrs["weight_names"] = [
            b"expanded_conv_depthwise_BN/gamma:0",
            b"expanded_conv_depthwise_BN/beta:0",
            b"expanded_conv_depthwise_BN/moving_mean:0",
            b"expanded_conv_depthwise_BN/moving_variance:0"]
        for nm, arr in (("gamma:0", gamma), ("beta:0", beta),
                        ("moving_mean:0", mean),
                        ("moving_variance:0", var)):
            g.create_dataset(f"expanded_conv_depthwise_BN/{nm}", data=arr)

    from idc_models_tpu.models.pretrained import maybe_load_pretrained

    m_f, m_g, v, x = _mobile_pair()
    params, state = maybe_load_pretrained(v.params, path, state=v.state,
                                          subtree=None)
    # the swap actually happened: our layout is (kh, kw, 1, C)
    loaded = np.asarray(params["expanded_conv_depthwise"]["kernel"])
    assert loaded.shape == (3, 3, 1, 32)
    np.testing.assert_array_equal(loaded,
                                  np.transpose(dw_keras, (0, 1, 3, 2)))
    np.testing.assert_array_equal(
        np.asarray(state["expanded_conv_depthwise_BN"]["mean"]), mean)
    y_f, _ = m_f.apply(params, state, x, train=False)
    y_g, _ = m_g.apply(params, state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_g),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# DenseNet: packed (concat-free) blocks vs the concat reference
# ---------------------------------------------------------------------------


def test_densenet_packed_matches_concat():
    m_p = densenet.densenet201_backbone(3, block_impl="packed")
    m_c = densenet.densenet201_backbone(3, block_impl="concat")
    v = m_p.init(jax.random.key(0))
    x = _rand(np.random.default_rng(5), (1, 64, 64, 3))
    y_p, _ = m_p.apply(v.params, v.state, x, train=False)
    y_c, _ = m_c.apply(v.params, v.state, x, train=False)
    assert y_p.shape == (1, 2, 2, 1920)
    # same channel layout, same conv inputs -> bit-identical is the bar
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_c),
                               rtol=0, atol=0)


def test_densenet_rejects_unknown_block_impl():
    with pytest.raises(ValueError, match="packed|concat"):
        densenet.densenet201_backbone(3, block_impl="fused")


# ---------------------------------------------------------------------------
# bench + docs structural gates
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_backbone_fused_structural():
    """The bench function itself on CPU: keys present, parity gate
    inside it passes, hbm_utilization correctly withheld (no roofline
    for a CPU device kind)."""
    import bench

    out = bench.bench_backbone_fused(False)
    for tag in ("mobile", "dense"):
        assert out[f"{tag}_fused_patches_per_sec"] > 0
        assert out[f"{tag}_fused_speedup"] > 0
        assert f"{tag}_fused_hbm_utilization" not in out
        assert f"{tag}_fused_patches_per_sec" in bench.HIGHER_IS_BETTER
        assert f"{tag}_fused_speedup" in bench.HIGHER_IS_BETTER
        assert (f"{tag}_fused_hbm_utilization"
                in bench.HIGHER_IS_BETTER)


def test_docs_cover_fused_kernels():
    """Satellite doc gate: the DESIGN section and the BENCHMARKS
    attribution update must exist (bench-key backtick coverage is
    enforced separately by test_observability's doc gate)."""
    root = Path(__file__).parent.parent
    design = (root / "docs" / "DESIGN.md").read_text()
    assert "Fused backbone kernels" in design
    assert "interpret" in design
    bench_md = (root / "docs" / "BENCHMARKS.md").read_text()
    for needle in ("`mobile_fused_patches_per_sec`",
                   "`dense_fused_speedup`",
                   "depthwise_chain_cost"):
        assert needle in bench_md, f"docs/BENCHMARKS.md missing {needle}"
