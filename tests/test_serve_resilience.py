"""The ISSUE-8 serving resilience layer against its hard contracts:

1. RECOVERY PARITY — greedy/seeded outputs are bit-identical across a
   poisoned-slot quarantine + retry AND across an injected mid-run
   engine crash + journal recovery (the serial `Generator` is the
   oracle, exactly as in tests/test_serve.py). The retry restarts from
   the prompt and the journal re-runs through the normal admission
   path, so the engine's serial-parity contract does all the work —
   these tests gate that the recovery paths actually preserve it.
2. DETERMINISTIC DRILLS — a `ServeFaultPlan` is a pure function of
   (plan, tick), so two runs of the same plan against the same trace
   produce identical failures, recoveries, and outputs.
3. HONEST DEGRADATION — the brownout controller escalates through its
   documented stages under sustained signal, restores with hysteresis,
   and every refusal is an explicit `shed` Result, never a silent drop.

Plus the satellites: submit-after-close raises, serve fault-spec parse
errors teach their own grammar, and prefix-cache warm restart across a
crash + rebuild serves hits that stay bit-identical.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import (
    BrownoutController, InjectedEngineCrash, LMServer, PrefixCache,
    Request, RetryPolicy, ServeFault, ServeFaultPlan, SlotEngine,
    load_journal, parse_serve_fault_spec, pending_requests,
)
from idc_models_tpu.serve.journal import RequestJournal

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw():
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=None, cache_dtype=jnp.float32)


def _serial_tokens(gen, prompt, steps, *, rng=None):
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps, rng=rng)
    return toks.tolist()[0]


# ---------------------------------------------------------------------------
# fault plan + spec grammar
# ---------------------------------------------------------------------------


def test_fault_plan_validation_and_burst_determinism():
    with pytest.raises(ValueError, match="unknown serve fault kind"):
        ServeFault("meteor", 1)
    with pytest.raises(ValueError, match="tick"):
        ServeFault("crash", -1)
    with pytest.raises(ValueError, match="seconds"):
        ServeFault("stall", 1, seconds=0.0)
    with pytest.raises(TypeError, match="ServeFault"):
        ServeFaultPlan(["crash:1"])
    plan = ServeFaultPlan([ServeFault("crash", 4),
                           ServeFault("burst", 2, n=3)], seed=7)
    assert [f.kind for f in plan.at(4)] == ["crash"]
    assert plan.at(2) == []                 # bursts are arrivals
    assert [f.kind for f in plan.bursts_at(2)] == ["burst"]
    assert plan.max_tick == 4
    # burst prompts are a pure function of (seed, tick, i): same plan
    # parameters -> the identical arrival wave, request for request
    plan2 = ServeFaultPlan([ServeFault("burst", 2, n=3)], seed=7)
    a = plan.burst_requests(plan.bursts_at(2)[0], vocab=VOCAB, t_max=SEQ)
    b = plan2.burst_requests(plan2.bursts_at(2)[0], vocab=VOCAB,
                             t_max=SEQ)
    assert [(r.id, r.prompt, r.max_new_tokens) for r in a] \
        == [(r.id, r.prompt, r.max_new_tokens) for r in b]
    assert all(r.id.startswith("!burst-") for r in a)
    # a different seed is a different wave
    c = ServeFaultPlan([ServeFault("burst", 2, n=3)], seed=8)
    assert [r.prompt for r in
            c.burst_requests(c.bursts_at(2)[0], vocab=VOCAB,
                             t_max=SEQ)] != [r.prompt for r in a]


def test_parse_serve_fault_spec_grammar_and_errors():
    """Satellite: every parse failure enumerates the valid kinds and
    shows the grammar — a mistyped drill flag teaches its own syntax."""
    plan = parse_serve_fault_spec(
        "nan_logits:3:1,stall:5-7:0.02,burst:2:16,crash:40", seed=3)
    kinds = sorted((f.kind, f.tick) for f in plan.faults)
    assert kinds == [("burst", 2), ("crash", 40), ("nan_logits", 3),
                     ("stall", 5), ("stall", 6), ("stall", 7)]
    assert plan.seed == 3
    nan = next(f for f in plan.faults if f.kind == "nan_logits")
    assert nan.slot == 1
    assert all(f.seconds == 0.02 for f in plan.faults
               if f.kind == "stall")
    assert next(f for f in plan.faults if f.kind == "burst").n == 16
    # +-joined tick lists
    assert [f.tick for f in
            parse_serve_fault_spec("crash:1+5").faults] == [1, 5]
    for bad, why in [
        ("meteor:3", "unknown fault kind"),
        ("nan_logits", "want kind:ticks"),
        ("crash:2:7", "takes no parameter"),
        ("stall:2:fast", "bad seconds parameter"),
        ("nan_logits:one:0", "bad ticks field"),
        # out-of-range values teach the same way as syntax errors
        ("stall:2:0", "seconds must be > 0"),
        ("burst:2:0", ">= 1"),
        ("nan_logits:3:-2", "slot must be >= 0"),
    ]:
        with pytest.raises(ValueError) as ei:
            parse_serve_fault_spec(bad)
        msg = str(ei.value)
        assert why in msg, (bad, msg)
        # the teaching part: all valid kinds + the grammar, every time
        for kind in ("nan_logits", "garbage_logits", "prefill_error",
                     "stall", "crash", "burst"):
            assert kind in msg, (bad, kind)
        assert "kind:ticks[:param]" in msg


# ---------------------------------------------------------------------------
# slot health + quarantine + retry
# ---------------------------------------------------------------------------


def test_engine_slot_health_codes_and_injection(devices, params):
    eng = SlotEngine(params, n_slots=2, **_kw())
    eng.warmup(2)
    eng.admit(0, (1, 2, 3), 4)
    assert eng.slot_health().tolist() == [0, 0]
    assert eng.slot_invariants_ok(0) and eng.slot_invariants_ok(1)
    eng.inject_slot_fault(0, "nan_logits")
    assert eng.slot_health().tolist()[0] == 1     # nonfinite_logits
    eng.inject_slot_fault(1, "garbage_logits")
    assert eng.slot_health().tolist()[1] == 2     # logit_magnitude
    with pytest.raises(ValueError, match="out of range"):
        eng.inject_slot_fault(9, "nan_logits")
    with pytest.raises(ValueError, match="kind"):
        eng.inject_slot_fault(0, "gremlins")


def test_poisoned_slot_quarantine_retry_bit_identical(devices, params):
    """The acceptance pair: a nan_logits fault poisons a running slot;
    the per-window health check quarantines ONLY that request, the
    retry policy re-admits it, and its final greedy output is
    bit-identical to an unfaulted serial run — while the other slot's
    request streams on untouched."""
    plan = ServeFaultPlan([ServeFault("nan_logits", 1, slot=0)])
    server = LMServer(params, n_slots=2, window=4, fault_plan=plan,
                      retry=RetryPolicy(max_retries=2, backoff_s=0.0),
                      **_kw())
    rng = np.random.default_rng(23)
    reqs = [Request(id=f"r{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + 2 * i)),
                    max_new_tokens=8)
            for i in range(2)]
    server.run([(0.0, r) for r in reqs])
    gen = Generator(params, **_kw())
    r0, r1 = server.poll("r0"), server.poll("r1")
    # the faulted request recovered: retried once, finished ok, output
    # identical to a run where the fault never happened
    assert r0.status == "ok" and r0.retried and r0.attempts == 2
    assert r0.tokens == _serial_tokens(gen, reqs[0].prompt, 8)
    # the innocent bystander never noticed
    assert r1.status == "ok" and not r1.retried and r1.attempts == 1
    assert r1.tokens == _serial_tokens(gen, reqs[1].prompt, 8)
    s = server.summary()
    assert s["serve_slot_faults"] == 1
    assert s["serve_retries"] == 1
    assert s["serve_faults_injected"] == 1


def test_quarantine_without_retry_finishes_honest_error(devices, params):
    """A fault plan with NO retry policy still arms the health checks:
    the poisoned request finishes with an explicit error/slot_fault
    status (never a silent wrong answer) and the server keeps
    serving."""
    plan = ServeFaultPlan([ServeFault("garbage_logits", 1, slot=0)])
    server = LMServer(params, n_slots=1, window=4, fault_plan=plan,
                      **_kw())
    server.run([(0.0, Request(id="a", prompt=(1, 2, 3),
                              max_new_tokens=8))])
    a = server.poll("a")
    assert a.status == "error" and a.finish_reason == "slot_fault"
    assert "logit_magnitude" in a.error and a.attempts == 1
    # still serviceable, still bit-exact
    gen = Generator(params, **_kw())
    server.submit(Request(id="b", prompt=(4, 5), max_new_tokens=5))
    server.drain()
    assert server.poll("b").tokens == _serial_tokens(gen, (4, 5), 5)


def test_retry_exhaustion_and_attempt_accounting(devices, params):
    """A slot poisoned on EVERY window exhausts its bounded retries and
    finishes error/slot_fault with the full attempt count on the
    Result — bounded recovery, not an infinite requeue loop."""
    plan = ServeFaultPlan([ServeFault("nan_logits", t, slot=0)
                           for t in range(1, 40)])
    server = LMServer(params, n_slots=1, window=4, fault_plan=plan,
                      retry=RetryPolicy(max_retries=2, backoff_s=0.0),
                      **_kw())
    server.run([(0.0, Request(id="doomed", prompt=(1, 2, 3),
                              max_new_tokens=6))])
    r = server.poll("doomed")
    assert r.status == "error" and r.finish_reason == "slot_fault"
    assert r.attempts == 3 and r.retried
    assert "attempt 3" in r.error
    assert server.summary()["serve_slot_faults"] == 3


def test_retry_respects_original_deadline(devices, params):
    """A retry whose backoff would land past the request's ORIGINAL
    deadline finishes timeout/deadline immediately instead of burning
    a slot on work the caller already gave up on."""
    now = [0.0]
    plan = ServeFaultPlan([ServeFault("nan_logits", 1, slot=0)])
    server = LMServer(params, n_slots=1, window=4, fault_plan=plan,
                      retry=RetryPolicy(max_retries=3, backoff_s=10.0),
                      clock=lambda: now[0], **_kw())
    server.submit(Request(id="late", prompt=(1, 2), max_new_tokens=8,
                          deadline_s=1.0))
    server.step()                       # admit, first window in flight
    server.step()                       # fault fires -> quarantine
    r = server.poll("late")
    assert r is not None, "deadline-blocked retry should finish now"
    assert r.status == "timeout" and r.finish_reason == "deadline"
    assert not r.retried                # the retry never happened


def test_prefill_error_quarantines_request_not_server(devices, params):
    """An injected prefill-chunk failure with a retry policy armed is
    REQUEST-scoped: the chunking request is quarantined and retried
    (output still bit-identical), nothing else dies."""
    plan = ServeFaultPlan([ServeFault("prefill_error", 0)])
    server = LMServer(params, n_slots=2, window=4, prefill_chunk=4,
                      fault_plan=plan,
                      retry=RetryPolicy(max_retries=1, backoff_s=0.0),
                      **_kw())
    prompt = tuple(range(1, 11))        # 3 chunks of 4
    server.run([(0.0, Request(id="p", prompt=prompt,
                              max_new_tokens=5))])
    r = server.poll("p")
    assert r.status == "ok" and r.retried and r.attempts == 2
    gen = Generator(params, **_kw())
    assert r.tokens == _serial_tokens(gen, prompt, 5)
    assert server.summary()["serve_slot_faults"] == 1


def test_fault_plan_replays_bit_identically(devices, params):
    """Same plan + same trace -> the same failures at the same cycles
    with the same recoveries and the same tokens, across two fresh
    servers (the whole point of declarative, tick-indexed faults)."""
    def one_run():
        plan = parse_serve_fault_spec(
            "nan_logits:1:0,stall:2:0.001,prefill_error:0")
        server = LMServer(params, n_slots=2, window=4, prefill_chunk=4,
                          fault_plan=plan,
                          retry=RetryPolicy(max_retries=2,
                                            backoff_s=0.0), **_kw())
        rng = np.random.default_rng(31)
        reqs = [Request(id=f"d{i}",
                        prompt=tuple(int(x) for x in
                                     rng.integers(0, VOCAB, 5 + 4 * i)),
                        max_new_tokens=6)
                for i in range(3)]
        server.run([(0.0, r) for r in reqs])
        summary = server.summary()
        return ([(r.id, server.poll(r.id).tokens,
                  server.poll(r.id).status, server.poll(r.id).attempts)
                 for r in reqs],
                {k: summary[k] for k in ("serve_slot_faults",
                                         "serve_retries",
                                         "serve_faults_injected")})
    first, second = one_run(), one_run()
    assert first == second


# ---------------------------------------------------------------------------
# journal + crash recovery
# ---------------------------------------------------------------------------


def test_journal_records_and_load_semantics(tmp_path):
    p = tmp_path / "wal.jsonl"

    class _E:
        rid, prompt, budget = "x", np.array([1, 2, 3]), 7
        eos_id, rng, trace_id = 4, 9, "t-1"

    with RequestJournal(p, progress_every=1) as j:
        j.record_submit(_E(), deadline_s=2.5)
        j.record_progress({"x": 3})
        j.record_progress({})                 # empty cycle: no record
        j.record_finish("x", "ok", reason="eos")
    loaded = load_journal(p)
    assert loaded["pending"] == [] and loaded["finished"] == {"x": "ok"}
    assert loaded["progress"] == {"x": 3}
    # an ENGINE-death finish (error/error) is recoverable; a shed or
    # slot_fault error is the request's honest final answer
    with RequestJournal(p) as j:
        j.record_submit(_E(), deadline_s=None)      # re-submit reopens
        j.record_finish("x", "error", reason="error")
    pend = pending_requests(p)
    assert [r.id for r in pend] == ["x"]
    r = pend[0]
    assert r.prompt == (1, 2, 3) and r.max_new_tokens == 7
    assert r.eos_id == 4 and r.seed == 9 and r.deadline_s is None
    assert r.trace_id == "t-1"
    with RequestJournal(p) as j:
        j.record_finish("x", "error", reason="slot_fault")
    assert pending_requests(p) == []
    # a torn WAL is a real error, not something to skip silently
    bad = tmp_path / "torn.jsonl"
    bad.write_text('{"event": "journal_submit", "id": "a"}\n{oops\n')
    with pytest.raises(ValueError, match="line 2"):
        load_journal(bad)
    with pytest.raises(ValueError, match="progress_every"):
        RequestJournal(tmp_path / "x.jsonl", progress_every=0)


def test_journal_progress_batches_and_strides(tmp_path):
    p = tmp_path / "wal.jsonl"
    with RequestJournal(p, progress_every=3) as j:
        for k in range(7):
            j.record_progress({"a": k + 1, "b": 2 * (k + 1)})
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    assert [r["event"] for r in recs] == ["journal_progress"] * 2
    # the stride drops intermediate cycles, never the per-rid mapping
    assert recs[-1]["tokens"] == {"a": 6, "b": 12}
    assert load_journal(p)["progress"] == {"a": 6, "b": 12}


def test_crash_journal_recovery_bit_identical(devices, params, tmp_path):
    """The tentpole acceptance: a hard mid-decode engine crash kills
    the server; a REBUILT server re-admits the journal's in-flight
    requests through the normal path and every request's greedy output
    — finished before or after the crash — is bit-identical to a run
    where the crash never happened."""
    wal = tmp_path / "journal.jsonl"
    plan = ServeFaultPlan([ServeFault("crash", 4)])
    a = LMServer(params, n_slots=2, window=4, fault_plan=plan,
                 journal=wal, **_kw())
    rng = np.random.default_rng(41)
    reqs = [Request(id=f"c{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + i)),
                    max_new_tokens=(4 if i == 0 else 16))
            for i in range(4)]
    with pytest.raises(InjectedEngineCrash):
        a.run([(0.0, r) for r in reqs])
    a.close()
    # c0 (one-window budget) finished before tick 4; the crash turned
    # the in-flight requests into honest error Results
    assert a.poll("c0").status == "ok"
    crashed = [r for r in a.results() if r.status == "error"]
    assert crashed and all("injected engine crash" in r.error
                           for r in crashed)
    # the journal knows exactly what to re-run: everything but c0
    pending = pending_requests(wal)
    assert sorted(r.id for r in pending) == ["c1", "c2", "c3"]

    b = LMServer(params, n_slots=2, window=4, journal=wal, **_kw())
    readmitted = b.resubmit_pending(wal)
    assert sorted(readmitted) == ["c1", "c2", "c3"]
    b.drain()
    b.close()
    gen = Generator(params, **_kw())
    for r in reqs:
        got = b.poll(r.id) or a.poll(r.id)
        assert got.status == "ok", r.id
        assert got.tokens == _serial_tokens(gen, r.prompt,
                                            r.max_new_tokens), r.id
    # recovery was journaled too: a second recovery finds nothing
    assert pending_requests(wal) == []


def test_prefix_cache_warm_restart_after_crash(devices, params,
                                               tmp_path):
    """Satellite: a server rebuilt after a crash can inherit the dead
    engine's prefix cache — recovered requests sharing a cached system
    prefix re-prefill only their suffix (hit-rate > 0) and the hits
    stay bit-identical to full recomputation."""
    wal = tmp_path / "journal.jsonl"
    sys_p = tuple(int(x) for x in
                  np.random.default_rng(43).integers(0, VOCAB, 8))
    reqs = [Request(id=f"w{i}", prompt=sys_p + (i,), max_new_tokens=4)
            for i in range(3)]
    plan = ServeFaultPlan([ServeFault("crash", 3)])
    a = LMServer(params, n_slots=1, window=4, prefill_chunk=8,
                 prefix_cache_mb=16.0, fault_plan=plan, journal=wal,
                 **_kw())
    with pytest.raises(InjectedEngineCrash):
        a.run([(0.0, r) for r in reqs])
    a.close()
    cache = a.engine.prefix_cache
    assert cache.nbytes > 0, "no snapshot survived to warm-restart from"
    hits_at_crash = cache.hits

    with pytest.raises(ValueError, match="prefix_cache OR"):
        LMServer(params, prefill_chunk=8, prefix_cache=cache,
                 prefix_cache_mb=1.0, **_kw())
    b = LMServer(params, n_slots=1, window=4, prefill_chunk=8,
                 prefix_cache=cache, journal=wal, **_kw())
    b.resubmit_pending(wal)
    b.drain()
    b.close()
    assert cache.hits > hits_at_crash, "warm restart never hit"
    gen = Generator(params, **_kw())
    for r in reqs:
        got = b.poll(r.id) or a.poll(r.id)
        assert got.status == "ok", r.id
        assert got.tokens == _serial_tokens(gen, r.prompt, 4), r.id


# ---------------------------------------------------------------------------
# brownout controller
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_brownout_escalates_and_restores_with_hysteresis():
    from idc_models_tpu.observe.metrics_registry import MetricsRegistry

    clk = _FakeClock()
    b = BrownoutController(queue_high=8, queue_low=2, clamp_tokens=4,
                           escalate_dwell_s=1.0, clear_after_s=5.0,
                           clock=clk, registry=MetricsRegistry())
    assert b.stage == 0 and not b.shedding and b.token_clamp is None
    # escalation: one stage per dwell while the signal fires
    assert b.evaluate(queue_depth=10) == 1      # pause_cache_writes
    clk.t = 0.5
    assert b.evaluate(queue_depth=10) == 1      # dwell not elapsed
    clk.t = 1.0
    assert b.evaluate(queue_depth=10) == 2      # clamp_tokens
    assert b.token_clamp == 4 and not b.shedding
    clk.t = 2.0
    assert b.evaluate(queue_depth=10) == 3      # shed
    assert b.shedding and b.max_stage_seen == 3
    clk.t = 3.0
    assert b.evaluate(queue_depth=10) == 3      # already at the top
    # queue below HIGH but above LOW: signal clear, but no clear timer
    clk.t = 4.0
    assert b.evaluate(queue_depth=5) == 3
    clk.t = 20.0
    assert b.evaluate(queue_depth=5) == 3, "restored into live load"
    # below the low watermark: the clear timer starts, one stage per
    # sustained clear_after_s
    clk.t = 21.0
    assert b.evaluate(queue_depth=1) == 3
    clk.t = 26.0
    assert b.evaluate(queue_depth=1) == 2
    clk.t = 27.0
    assert b.evaluate(queue_depth=1) == 2       # not another 5 s yet
    clk.t = 31.0
    assert b.evaluate(queue_depth=1) == 1
    # a re-fire mid-recovery resets the clear timer
    clk.t = 32.0
    assert b.evaluate(queue_depth=9) == 2
    clk.t = 40.0
    b.evaluate(queue_depth=0)
    directions = [t["direction"] for t in b.transitions]
    assert directions.count("escalate") == 4
    assert directions.count("restore") == 2
    assert all(t["stage_name"] in ("normal", "pause_cache_writes",
                                   "clamp_tokens", "shed")
               for t in b.transitions)


def test_brownout_validation_and_prefix_cache_pause():
    from idc_models_tpu.observe.metrics_registry import MetricsRegistry

    with pytest.raises(ValueError, match="at least one signal"):
        BrownoutController(registry=MetricsRegistry())
    with pytest.raises(ValueError, match="queue_low < queue_high"):
        BrownoutController(queue_high=4, queue_low=4,
                           registry=MetricsRegistry())
    with pytest.raises(ValueError, match="clamp_tokens"):
        BrownoutController(queue_high=4, clamp_tokens=0,
                           registry=MetricsRegistry())
    cache = PrefixCache(max_bytes=1 << 20, chunk=8,
                        registry=MetricsRegistry())
    clk = _FakeClock()
    b = BrownoutController(queue_high=2, queue_low=0, clock=clk,
                           escalate_dwell_s=0.0, clear_after_s=1.0,
                           prefix_cache=cache,
                           registry=MetricsRegistry())
    b.evaluate(queue_depth=5)
    assert cache.writes_paused                  # stage 1 side effect
    assert not cache.insert(np.arange(8), caches=(), logits=None)
    clk.t = 10.0
    b.evaluate(queue_depth=0)                   # clear timer starts
    clk.t = 12.0
    b.evaluate(queue_depth=0)                   # sustained clear
    assert b.stage == 0 and not cache.writes_paused


def test_brownout_sheds_submits_and_clamps_budget(devices, params):
    """The server-level loop: a queue-watermark brownout refuses new
    submits with an explicit `shed` Result (poll() answers for it, the
    run completes, nothing hangs) and clamps admitted budgets at stage
    2, with both visible in the summary rollup."""
    clk = _FakeClock()
    b = BrownoutController(queue_high=3, queue_low=0, clamp_tokens=2,
                           escalate_dwell_s=0.0, clear_after_s=1e9,
                           clock=clk)
    server = LMServer(params, n_slots=1, window=4, brownout=b,
                      clock=clk, max_queue_depth=64, **_kw())
    # drive the controller to shed by hand (deterministic), then submit
    for _ in range(3):
        b.evaluate(queue_depth=10)
    assert b.shedding
    assert not server.submit(Request(id="s0", prompt=(1, 2),
                                     max_new_tokens=4))
    shed = server.poll("s0")
    assert shed.status == "shed" and shed.finish_reason == "shed"
    assert shed.tokens == []
    # run() treats a shed as terminal, not backpressure to wait out
    out = server.run([(0.0, Request(id="s1", prompt=(3,),
                                    max_new_tokens=4))])
    assert [r.status for r in out] == ["shed"]
    s = server.summary()
    assert s["serve_shed"] == 2
    # step back to clamp_tokens: admissions get the shortened budget
    b._transition(2, clk(), "test")
    server.submit(Request(id="s2", prompt=(1, 2, 3), max_new_tokens=9))
    server.drain()
    r = server.poll("s2")
    assert r.status == "ok" and len(r.tokens) == 2
    assert server.summary()["serve_clamped"] == 1
    # and the clamped stream is the serial stream, truncated
    gen = Generator(params, **_kw())
    assert r.tokens == _serial_tokens(gen, (1, 2, 3), 2)
    # a SHED id may retry once the brownout clears (the one terminal
    # state that consumed no engine work): the stale shed Result stops
    # answering poll() the moment the resubmit is accepted
    b._transition(0, clk(), "test")
    assert server.submit(Request(id="s0", prompt=(1, 2),
                                 max_new_tokens=3))
    assert server.poll("s0") is None        # queued now, not shed
    server.drain()
    assert server.poll("s0").status == "ok"
    # every OTHER terminal state still refuses id reuse
    with pytest.raises(ValueError, match="already used"):
        server.submit(Request(id="s2", prompt=(1,), max_new_tokens=2))


def test_burst_fault_floods_and_brownout_sheds(devices, params):
    """End to end: declarative burst arrivals flood the queue, the
    watermark brownout escalates to shed, and every refused request is
    an explicit shed Result — the clean requests still finish ok."""
    plan = ServeFaultPlan([ServeFault("burst", t, n=6, prompt_len=3,
                                      budget=12)
                           for t in range(1, 5)])
    b = BrownoutController(queue_high=6, queue_low=1, clamp_tokens=4,
                           escalate_dwell_s=0.0, clear_after_s=0.02)
    server = LMServer(params, n_slots=2, window=4, fault_plan=plan,
                      brownout=b, max_queue_depth=64, **_kw())
    results = server.run([(0.0, Request(id=f"b{i}", prompt=(1 + i, 2),
                                        max_new_tokens=6))
                          for i in range(4)])
    s = server.summary()
    assert s["serve_faults_injected"] == 4          # the burst ticks
    assert s["serve_shed"] > 0 and b.max_stage_seen == 3
    by_id = {r.id: r for r in results}
    assert all(by_id[f"b{i}"].status in ("ok", "shed")
               for i in range(4))
    assert any(by_id[f"b{i}"].status == "ok" for i in range(4))
    shed_bursts = [r for r in server.results()
                   if r.id.startswith("!burst") and r.status == "shed"]
    assert shed_bursts, "the flood itself never got shed"


# ---------------------------------------------------------------------------
# close() satellite
# ---------------------------------------------------------------------------


def test_submit_after_close_raises(devices, params, tmp_path):
    """Satellite: submit() after close() raises a clean RuntimeError
    instead of enqueueing into a loop nobody will ever tick again —
    and close() flushes the journal."""
    server = LMServer(params, n_slots=1, window=4,
                      journal=tmp_path / "wal.jsonl", **_kw())
    server.submit(Request(id="a", prompt=(1, 2), max_new_tokens=3))
    server.drain()
    server.close()
    with pytest.raises(RuntimeError, match="close"):
        server.submit(Request(id="b", prompt=(3,), max_new_tokens=3))
    # the WAL closed with the finish on disk
    assert load_journal(tmp_path / "wal.jsonl")["finished"] == {
        "a": "ok"}
