"""The self-healing round driver (federated/driver.py): healthy runs,
divergence rollback, timeout retry with a reseeded client subset,
bounded retries, and health-event logging."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import (
    DriverConfig, RoundFailure, initialize_server, make_fedavg_round,
    run_rounds,
)
from idc_models_tpu.federated.driver import reseeded_subset
from idc_models_tpu.models import small_cnn
from idc_models_tpu.observe import JsonlLogger
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N = 8


@pytest.fixture(scope="module")
def fed():
    imgs, labels = synthetic.make_idc_like(N * 16, size=10, seed=0)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), N, iid=True,
                               seed=0)
    w = np.full((N,), 16.0, np.float32)
    model = small_cnn(10, 3, 1)
    mesh = meshlib.client_mesh(N)
    rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                            mesh, local_epochs=1, batch_size=16)
    return model, rnd, ci, cl, w


def _nan_server(s):
    return s.replace(params=jax.tree.map(lambda x: x * jnp.nan, s.params))


def test_healthy_run_and_history(fed, tmp_path):
    model, rnd, ci, cl, w = fed
    logger = JsonlLogger(tmp_path / "run.jsonl")
    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(rnd, server, ci, cl, w,
                     config=DriverConfig(rounds=3), seed=1,
                     eval_fn=lambda s: {"probe": 1.0}, logger=logger)
    logger.close()
    assert int(res.server.round) == 3
    assert [h["round"] for h in res.history] == [0, 1, 2]
    assert all(h["attempts"] == 1 and h["probe"] == 1.0
               for h in res.history)
    assert all(e["status"] == "ok" for e in res.events)
    recs = [json.loads(l)
            for l in (tmp_path / "run.jsonl").read_text().splitlines()]
    assert sum(r["event"] == "round" for r in recs) == 3
    assert sum(r["event"] == "round_health" for r in recs) == 3


def test_divergent_round_rolls_back_and_completes(fed):
    """An injected divergent round triggers rollback to the last good
    server state; the retry heals it and training completes the
    remaining rounds on finite params."""
    model, rnd, ci, cl, w = fed
    attempts = []

    def flaky(server, images, labels, weights, rng):
        s, m = rnd(server, images, labels, weights, rng)
        r = int(s.round) - 1
        a = sum(1 for x in attempts if x == r)
        attempts.append(r)
        if r == 1 and a == 0:
            s = _nan_server(s)          # round 1 diverges on try 0
        return s, m

    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(flaky, server, ci, cl, w,
                     config=DriverConfig(rounds=3), seed=1)
    statuses = [(e["round"], e["attempt"], e["status"])
                for e in res.events]
    assert (1, 0, "diverged") in statuses
    assert (1, 1, "ok") in statuses
    assert int(res.server.round) == 3
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(res.server.params)))
    assert res.history[1]["attempts"] == 2


def test_loss_spike_rolls_back(fed):
    model, rnd, ci, cl, w = fed
    calls = []

    def spiky(server, images, labels, weights, rng):
        s, m = rnd(server, images, labels, weights, rng)
        calls.append(int(s.round) - 1)
        if int(s.round) - 1 == 1 and calls.count(1) == 1:
            m = dict(m)
            m["loss"] = jnp.float32(1e9)   # finite but exploded
        return s, m

    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(spiky, server, ci, cl, w,
                     config=DriverConfig(rounds=3, loss_spike_ratio=5.0),
                     seed=1)
    assert [e["status"] for e in res.events
            if e["round"] == 1] == ["diverged", "ok"]
    assert int(res.server.round) == 3


def test_timeout_retries_with_reseeded_subset(fed):
    """A round past its wall budget is discarded and retried with a
    RESEEDED, smaller client subset (deterministic per (seed, round,
    attempt))."""
    model, rnd, ci, cl, w = fed
    t = [0.0]
    seen = []

    def slow(server, images, labels, weights, rng):
        seen.append(np.asarray(jax.device_get(weights)).copy())
        t[0] += 100.0 if len(seen) == 1 else 0.1
        return rnd(server, images, labels, weights, rng)

    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(slow, server, ci, cl, w,
                     config=DriverConfig(rounds=2, timeout_s=10.0,
                                         timeout_exempt_first=False),
                     seed=1, clock=lambda: t[0])
    assert [(e["round"], e["attempt"], e["status"])
            for e in res.events][:2] == [(0, 0, "timeout"), (0, 1, "ok")]
    # attempt 1 ran a strict subset of the attempt-0 population
    assert (seen[1] > 0).sum() < (seen[0] > 0).sum()
    assert np.all(w[seen[1] > 0] > 0)
    # and that subset is deterministic
    np.testing.assert_array_equal(
        seen[1], reseeded_subset(w, 1, 0, 1, 0.7))
    assert int(res.server.round) == 2

    # default config: the chronologically FIRST attempt is exempt (its
    # wall time is dominated by XLA compiles, not straggling), so the
    # same slow first round passes and no retry happens
    t[0] = 0.0
    seen.clear()
    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(slow, server, ci, cl, w,
                     config=DriverConfig(rounds=2, timeout_s=10.0),
                     seed=1, clock=lambda: t[0])
    assert all(e["status"] == "ok" for e in res.events)
    assert len(seen) == 2


def test_bounded_retries_then_raise(fed):
    model, rnd, ci, cl, w = fed

    def dead(server, images, labels, weights, rng):
        s, m = rnd(server, images, labels, weights, rng)
        return _nan_server(s), m

    server = initialize_server(model, jax.random.key(0))
    with pytest.raises(RoundFailure, match="failed 2 attempt"):
        run_rounds(dead, server, ci, cl, w,
                   config=DriverConfig(rounds=2, max_attempts=2), seed=1)

    # a raising round_fn is retried too, then chained into the failure
    def broken(server, images, labels, weights, rng):
        raise RuntimeError("device fell off")

    server = initialize_server(model, jax.random.key(0))
    with pytest.raises(RoundFailure) as ei:
        run_rounds(broken, server, ci, cl, w,
                   config=DriverConfig(rounds=1, max_attempts=2), seed=1)
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert int(ei.value.server.round) == 0      # rollback anchor exposed


def test_driver_checkpoints_and_resumes(fed, tmp_path):
    from idc_models_tpu.train import checkpoint_exists, restore_checkpoint

    model, rnd, ci, cl, w = fed
    path = tmp_path / "server"
    server = initialize_server(model, jax.random.key(0))
    res = run_rounds(rnd, server, ci, cl, w,
                     config=DriverConfig(rounds=3, checkpoint_path=path,
                                         checkpoint_every=2), seed=1)
    assert checkpoint_exists(path)
    restored = restore_checkpoint(
        path, jax.device_get(initialize_server(model, jax.random.key(9))))
    assert int(restored.round) == 3
    # resuming a finished run is a no-op, not an error
    res2 = run_rounds(rnd, restored, ci, cl, w,
                      config=DriverConfig(rounds=3), seed=1)
    assert res2.history == [] and int(res2.server.round) == 3
