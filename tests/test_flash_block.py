"""The fused flash block kernel == the jnp online-softmax recurrence,
standalone and inside the ring, values and gradients (interpret mode on
the CPU mesh; the same kernel compiles for real on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ops import flash_block_kernel as fbk
from idc_models_tpu.ring_attention import full_attention, make_ring_attention

B, T, H, D = 2, 256, 2, 32


def _inputs(seed=0, t_q=T, t_k=T):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(0, 1, (B, t_q, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (B, t_k, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (B, t_k, H, D)), jnp.float32)
    # a mid-stream carry (as if one block was already folded in), so the
    # test covers the corr-rescale path, not just the fresh-start one
    m = jnp.asarray(rng.normal(0, 1, (B, H, t_q)), jnp.float32)
    l = jnp.asarray(rng.uniform(0.5, 2.0, (B, H, t_q)), jnp.float32)
    acc = jnp.asarray(rng.normal(0, 1, (B, t_q, H, D)), jnp.float32)
    return q, k, v, m, l, acc


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_reference(causal):
    q, k, v, m, l, acc = _inputs()
    offsets = jnp.asarray([128, 0], jnp.int32)
    upd = fbk.make_flash_block_update(scale=D ** -0.5, causal=causal,
                                      interpret=True)
    got = upd(q, k, v, m, l, acc, offsets)
    want = fbk.reference_impl(q, k, v, m, l, acc, offsets,
                              scale=D ** -0.5, causal=causal)
    for g, w, name in zip(got, want, ("m", "l", "acc")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-5, atol=1e-5, err_msg=name)


def test_kernel_gradients_match_reference():
    q, k, v, m, l, acc = _inputs(seed=2)
    offsets = jnp.asarray([0, 0], jnp.int32)
    upd = fbk.make_flash_block_update(scale=D ** -0.5, causal=True,
                                      interpret=True)

    def loss_of(fn):
        def loss(q, k, v):
            m2, l2, a2 = fn(q, k, v, m, l, acc, offsets)
            return jnp.sum(a2 ** 2) + jnp.sum(l2 ** 2) + jnp.sum(m2)
        return loss

    ref = lambda *a: fbk.reference_impl(*a, scale=D ** -0.5, causal=True)
    g_k = jax.grad(loss_of(upd), (0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_of(ref), (0, 1, 2))(q, k, v)
    # the kernel's chunked forward and the reference differ by fp
    # reassociation; those tiny output deltas feed the cotangents, so
    # the comparison is to fp tolerance, not bitwise
    for a, b, name in zip(g_k, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_with_pallas_blocks_matches_full(devices, causal):
    """T=1024 over 8 devices -> t_local=128 (the kernel's tile): the
    pallas-block ring must equal full attention AND the jnp-block ring."""
    rng = np.random.default_rng(5)
    t = 1024
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, t, 2, 32)), jnp.float32)
               for _ in range(3))
    mesh = meshlib.seq_mesh(8)
    out_p = make_ring_attention(mesh, causal=causal,
                                block_impl="pallas")(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    out_j = make_ring_attention(mesh, causal=causal,
                                block_impl="jnp")(q, k, v)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j),
                               rtol=1e-5, atol=1e-5)


def test_ring_pallas_gradients(devices):
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, 1024, 2, 32)),
                           jnp.float32) for _ in range(3))
    mesh = meshlib.seq_mesh(8)
    ring_p = make_ring_attention(mesh, causal=True, block_impl="pallas")
    g_p = jax.grad(lambda a, b, c: jnp.sum(ring_p(a, b, c) ** 2),
                   (0, 1, 2))(q, k, v)
    g_f = jax.grad(lambda a, b, c: jnp.sum(
        full_attention(a, b, c, causal=True) ** 2), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_p, g_f, "qkv"):
        assert bool(jnp.all(jnp.isfinite(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_block_grads_kernel_matches_reference(causal):
    """The two backward kernels == the dense jnp mirror of the flash
    backward formula, for one visiting block (interpret mode)."""
    rng = np.random.default_rng(4)
    mk = lambda *s: jnp.asarray(rng.normal(0, 1, s), jnp.float32)
    q, k, v, do = (mk(B, T, H, D) for _ in range(4))
    L = mk(B, H, T) + 3.0     # any finite logsumexp works for parity
    Dr = mk(B, H, T)
    offsets = jnp.asarray([128, 0], jnp.int32)
    gfn = fbk.make_flash_block_grads(scale=D ** -0.5, causal=causal,
                                     interpret=True)
    got = gfn(q, k, v, do, L, Dr, offsets)
    want = fbk.block_grads_reference(q, k, v, do, L, Dr, offsets,
                                     scale=D ** -0.5, causal=causal)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_block_grads_reference_matches_autodiff(causal):
    """With L/D taken from a real forward, the flash backward formula is
    THE gradient of full attention (single block = whole sequence)."""
    rng = np.random.default_rng(6)
    mk = lambda *s: jnp.asarray(rng.normal(0, 1, s), jnp.float32)
    q, k, v, do = (mk(B, T, H, D) for _ in range(4))
    scale = D ** -0.5
    out, vjp = jax.vjp(
        lambda q_, k_, v_: full_attention(q_, k_, v_, causal=causal),
        q, k, v)
    want = vjp(do)
    # recover L (per-row logsumexp) and D from the forward
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))[None, None]
        s = jnp.where(mask, s, -jnp.inf)
    L = jax.nn.logsumexp(s, axis=-1)
    Dr = jnp.einsum("bqhd,bqhd->bhq", do, out)
    got = fbk.block_grads_reference(q, k, v, do, L, Dr,
                                    jnp.asarray([0, 0], jnp.int32),
                                    scale=scale, causal=causal)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_zigzag_ring_pallas_gradients(devices):
    """Grads through the zigzag pallas ring (the quarter-schedule
    backward with riding dk/dv accumulators) == full attention."""
    from idc_models_tpu.ring_attention import from_zigzag, to_zigzag

    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, 2048, 2, 32)),
                           jnp.float32) for _ in range(3))
    mesh = meshlib.seq_mesh(8)
    ring = make_ring_attention(mesh, causal=True, layout="zigzag",
                               block_impl="pallas")

    def ring_loss(q, k, v):
        zz = [to_zigzag(x, 8) for x in (q, k, v)]
        return jnp.sum(jnp.square(from_zigzag(ring(*zz), 8)))

    g_p = jax.grad(ring_loss, (0, 1, 2))(q, k, v)
    g_f = jax.grad(lambda a, b, c: jnp.sum(
        full_attention(a, b, c, causal=True) ** 2), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_p, g_f, "qkv"):
        assert bool(jnp.all(jnp.isfinite(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


def _intermediate_shapes(closed):
    """All eqn output shapes in a jaxpr, recursing into sub-jaxprs
    (loops, custom_vjp calls, pallas kernels, shard_map bodies)."""
    shapes = []

    def sub(x):
        # duck-typed: ClosedJaxpr has .jaxpr, Jaxpr has .eqns
        if hasattr(x, "jaxpr"):
            yield x.jaxpr
        elif hasattr(x, "eqns"):
            yield x
        elif isinstance(x, (list, tuple)):
            for e in x:
                yield from sub(e)

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    shapes.append(tuple(aval.shape))
            for p in eqn.params.values():
                for j in sub(p):
                    walk(j)

    walk(closed.jaxpr)
    return shapes


def test_pallas_backward_is_blockwise(devices):
    """THE memory claim of the flash backward: no [t_local, t_local]
    intermediate exists anywhere in the fwd+bwd program — only kernel
    tiles. The jnp path is the positive control: its rematerialized
    backward DOES build the quadratic score tensor, so the detector is
    proven able to see one."""
    t, n = 8192, 8
    t_local = t // n
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, t, 2, 32)),
                           jnp.float32) for _ in range(3))
    mesh = meshlib.seq_mesh(n)

    def quad(shapes):
        return [s for s in shapes
                if len(s) >= 2 and s[-1] >= t_local and s[-2] >= t_local]

    ring_p = make_ring_attention(mesh, causal=True, block_impl="pallas")
    jp = jax.make_jaxpr(jax.grad(
        lambda a, b, c: jnp.sum(ring_p(a, b, c) ** 2), (0, 1, 2)))(q, k, v)
    assert not quad(_intermediate_shapes(jp)), (
        f"pallas backward materializes {quad(_intermediate_shapes(jp))}")

    ring_j = make_ring_attention(mesh, causal=True, block_impl="jnp")
    jj = jax.make_jaxpr(jax.grad(
        lambda a, b, c: jnp.sum(ring_j(a, b, c) ** 2), (0, 1, 2)))(q, k, v)
    assert quad(_intermediate_shapes(jj)), (
        "detector failed its positive control: jnp path shows no "
        "quadratic intermediate")


def test_non_tile_multiple_rejected(devices):
    q, k, v, m, l, acc = _inputs(t_q=96, t_k=96)
    upd = fbk.make_flash_block_update(scale=D ** -0.5, causal=False,
                                      interpret=True)
    with pytest.raises(ValueError, match="multiples of 128"):
        upd(q, k, v, m, l, acc, jnp.asarray([0, 0], jnp.int32))
