"""Static scan: no silent failure swallowing in idc_models_tpu/.

A bare ``except:`` (catches KeyboardInterrupt/SystemExit too) or an
``except Exception: pass``-style handler whose body discards the error
turns every future bug at that site into silent corruption — the exact
failure class this PR's robustness layer exists to eliminate. This test
walks the package AST and fails on any new one outside the explicit
allowlist, so silent-failure handlers cannot regress in through review.

Allowlisted sites must be best-effort BY DESIGN (a fallback path
follows, or the handler runs inside cleanup for an error that is
already propagating) — each entry documents why.
"""

import ast
from pathlib import Path

PACKAGE = Path(__file__).parent.parent / "idc_models_tpu"

# (relative path, enclosing function) -> why swallowing is correct there
ALLOWLIST = {
    ("observe/logging.py", "_jsonable"):
        "best-effort scalar coercion; falls through to the array/repr "
        "paths below — the record is still written",
    ("serve/scheduler.py", "_abort_running"):
        "engine-failure cleanup: release() may fail on the already-"
        "broken engine, but every slot must still be marked failed "
        "while the ORIGINAL engine error propagates to the caller",
}

_BROAD = {"Exception", "BaseException"}


def _enclosing_function(stack):
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name
    return "<module>"


def _is_swallowing(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/continue/constant-expressions (docstrings,
    Ellipsis): the caught error influences nothing."""
    return all(
        isinstance(n, (ast.Pass, ast.Continue))
        or (isinstance(n, ast.Expr) and isinstance(n.value, ast.Constant))
        for n in handler.body)


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(isinstance(t, ast.Name) and t.id in _BROAD for t in types)


def _scan(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    violations = []

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ExceptHandler):
                bare = child.type is None
                swallowing = (_catches_broadly(child)
                              and _is_swallowing(child))
                if bare or swallowing:
                    key = (rel, _enclosing_function(stack))
                    if bare or key not in ALLOWLIST:
                        violations.append(
                            (rel, child.lineno,
                             "bare except" if bare
                             else "except Exception: pass",
                             _enclosing_function(stack)))
            walk(child, stack + [child])

    walk(tree, [])
    return violations


def test_no_silent_exception_swallowing():
    files = sorted(PACKAGE.rglob("*.py"))
    assert files, f"package not found at {PACKAGE}"
    violations = []
    for f in files:
        violations.extend(_scan(f))
    assert not violations, (
        "silent failure handlers found (add real handling, narrow the "
        "exception type, or — only for genuinely best-effort sites — "
        f"extend the documented ALLOWLIST): {violations}")


def test_allowlist_entries_still_exist():
    """A stale allowlist entry means the site was fixed or moved —
    prune it so the list stays an honest inventory."""
    live = set()
    for f in sorted(PACKAGE.rglob("*.py")):
        rel = str(f.relative_to(PACKAGE)).replace("\\", "/")
        tree = ast.parse(f.read_text(), filename=str(f))

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if (isinstance(child, ast.ExceptHandler)
                        and _catches_broadly(child)
                        and _is_swallowing(child)):
                    live.add((rel, _enclosing_function(stack)))
                walk(child, stack + [child])

        walk(tree, [])
    stale = set(ALLOWLIST) - live
    assert not stale, f"allowlist entries no longer match any code: {stale}"
