"""Static scans over idc_models_tpu/: no silent failure swallowing, no
stray `print(` output.

A bare ``except:`` (catches KeyboardInterrupt/SystemExit too) or an
``except Exception: pass``-style handler whose body discards the error
turns every future bug at that site into silent corruption — the exact
failure class this PR's robustness layer exists to eliminate. This test
walks the package AST and fails on any new one outside the explicit
allowlist, so silent-failure handlers cannot regress in through review.

Likewise for output (ISSUE 5): the observability layer routes run
output through `observe.JsonlLogger`, the span tracer, and the metrics
registry — a bare ``print(`` in library code is invisible to every one
of those. The print scan bans new ones outside the documented
allowlist (reference-parity prints like the Timer line, and the CLI,
whose epilogues ARE the product surface).

Allowlisted sites must be best-effort / user-facing BY DESIGN — each
entry documents why.
"""

import ast
from pathlib import Path

PACKAGE = Path(__file__).parent.parent / "idc_models_tpu"

# (relative path, enclosing function) -> why swallowing is correct there
ALLOWLIST = {
    ("observe/logging.py", "_jsonable"):
        "best-effort scalar coercion; falls through to the array/repr "
        "paths below — the record is still written",
    ("serve/scheduler.py", "_abort_running"):
        "engine-failure cleanup: release() may fail on the already-"
        "broken engine, but every slot must still be marked failed "
        "while the ORIGINAL engine error propagates to the caller",
}

# (relative path, enclosing function) -> why a print is correct there.
# A file mapped to "*" allowlists every function in it.
PRINT_ALLOWLIST = {
    ("cli.py", "*"):
        "the CLI's stdout/stderr epilogues ARE its product surface "
        "(summary lines, usage errors, progress) — the reference's "
        "scripts print the same way; structured copies go through the "
        "jsonl logger alongside",
    ("observe/timer.py", "__exit__"):
        "the reference-parity '{name} took {t} seconds' line (SURVEY.md "
        "C17) — byte-for-byte print parity is the contract",
    ("train/loop.py", "fit"):
        "Keras-`fit`-style per-epoch progress + resume notice, the "
        "reference's model.fit console behavior; the jsonl logger "
        "carries the structured copy",
    ("train/loop.py", "two_phase_fit"):
        "reference-parity console output (initial floor, raw history "
        "dicts — dist_model_tf_vgg.py:100-101,131-132) plus the "
        "feature-cache fallback notice",
    ("federated/driver.py", "run_rounds"):
        "opt-in (verbose=True) stderr healing notice while the round "
        "retries — the structured record goes to round_health",
    ("models/pretrained.py", "maybe_load_pretrained"):
        "load confirmation the CLI tests key on ('loaded pretrained "
        "weights'); mismatches go through warnings.warn",
}

_BROAD = {"Exception", "BaseException"}


def _enclosing_function(stack):
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name
    return "<module>"


def _is_swallowing(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/continue/constant-expressions (docstrings,
    Ellipsis): the caught error influences nothing."""
    return all(
        isinstance(n, (ast.Pass, ast.Continue))
        or (isinstance(n, ast.Expr) and isinstance(n.value, ast.Constant))
        for n in handler.body)


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(isinstance(t, ast.Name) and t.id in _BROAD for t in types)


def _scan(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    violations = []

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ExceptHandler):
                bare = child.type is None
                swallowing = (_catches_broadly(child)
                              and _is_swallowing(child))
                if bare or swallowing:
                    key = (rel, _enclosing_function(stack))
                    if bare or key not in ALLOWLIST:
                        violations.append(
                            (rel, child.lineno,
                             "bare except" if bare
                             else "except Exception: pass",
                             _enclosing_function(stack)))
            walk(child, stack + [child])

    walk(tree, [])
    return violations


def test_no_silent_exception_swallowing():
    files = sorted(PACKAGE.rglob("*.py"))
    assert files, f"package not found at {PACKAGE}"
    violations = []
    for f in files:
        violations.extend(_scan(f))
    assert not violations, (
        "silent failure handlers found (add real handling, narrow the "
        "exception type, or — only for genuinely best-effort sites — "
        f"extend the documented ALLOWLIST): {violations}")


def _scan_prints(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    if (rel, "*") in PRINT_ALLOWLIST:
        return [], set()
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id == "print"):
                key = (rel, _enclosing_function(stack))
                live.add(key)
                if key not in PRINT_ALLOWLIST:
                    violations.append((rel, child.lineno, key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_no_bare_prints():
    """Library output goes through the logger / tracer / registry
    (observe/), not print — a print is invisible to every export path
    and unconditionally spams embedding applications. The documented
    allowlist holds the reference-parity prints and the CLI."""
    violations, live = [], set()
    for f in sorted(PACKAGE.rglob("*.py")):
        v, l = _scan_prints(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "bare print( in library code (route it through "
        "observe.JsonlLogger / trace spans / the metrics registry, or "
        "— only for genuinely user-facing reference-parity output — "
        f"extend the documented PRINT_ALLOWLIST): {violations}")


def test_print_allowlist_entries_still_exist():
    """A stale print-allowlist entry means the site was fixed or moved
    — prune it so the list stays an honest inventory."""
    live = set()
    for f in sorted(PACKAGE.rglob("*.py")):
        _, l = _scan_prints(f)
        live.update(l)
    whole_file = {rel for rel, fn in PRINT_ALLOWLIST if fn == "*"}
    present_files = {
        str(f.relative_to(PACKAGE)).replace("\\", "/")
        for f in PACKAGE.rglob("*.py")}
    stale = {(rel, fn) for rel, fn in PRINT_ALLOWLIST
             if fn != "*" and (rel, fn) not in live}
    stale |= {(rel, "*") for rel in whole_file
              if rel not in present_files}
    assert not stale, f"print allowlist entries match no code: {stale}"


# -- metrics hygiene (ISSUE 7 satellites) -----------------------------------
#
# 1. Every metrics-registry registration must carry non-empty help text:
#    the /metrics exposition renders `# HELP` from it, and a bare metric
#    name is exactly the kind of operational surface that rots into
#    "nobody knows what this counts".
# 2. `time.time()` is banned in serve/ + observe/ outside a documented
#    wall-clock-anchor allowlist: hot-path intervals must come from
#    time.monotonic()/perf_counter (wall time jumps under NTP slew and
#    breaks durations); wall clocks are for ANCHORING records to epoch
#    time, which each allowlisted site documents.

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}

# (relative path, enclosing function) -> why wall-clock is correct there
TIME_TIME_ALLOWLIST = {
    ("observe/logging.py", "log"):
        "every jsonl record's `ts` anchor — the cross-run comparison "
        "axis; never used for durations",
    ("observe/trace.py", "__init__"):
        "the tracer's one wall anchor (wall_t0) mapping monotonic span "
        "offsets to epoch time; durations stay on the injected "
        "monotonic clock",
    ("observe/metrics_registry.py", "write_snapshot"):
        "the standalone snapshot file's header timestamp",
}


def _scan_metric_help(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    violations = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _METRIC_FACTORIES
                    and child.args
                    and isinstance(child.args[0], ast.Constant)
                    and isinstance(child.args[0].value, str)):
                help_node = None
                if len(child.args) > 1:
                    help_node = child.args[1]
                else:
                    for kw in child.keywords:
                        if kw.arg == "help":
                            help_node = kw.value
                ok = (isinstance(help_node, ast.Constant)
                      and isinstance(help_node.value, str)
                      and help_node.value.strip())
                if not ok:
                    violations.append(
                        (rel, child.lineno, child.args[0].value))
            walk(child)

    walk(tree)
    return violations


def test_metric_registrations_carry_help_text():
    violations = []
    for f in sorted(PACKAGE.rglob("*.py")):
        if f.name == "metrics_registry.py":
            continue      # the factory definitions, not registrations
        violations.extend(_scan_metric_help(f))
    assert not violations, (
        "metrics registered without help text (the /metrics exposition "
        "renders '# HELP' from it — every instrument must say what it "
        f"counts): {violations}")


def _scan_time_time(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "time"
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id == "time"):
                key = (rel, _enclosing_function(stack))
                live.add(key)
                if key not in TIME_TIME_ALLOWLIST:
                    violations.append((rel, child.lineno, key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_no_wall_clock_in_serve_observe_hot_paths():
    violations, live = [], set()
    for sub in ("serve", "observe"):
        for f in sorted((PACKAGE / sub).rglob("*.py")):
            v, l = _scan_time_time(f)
            violations.extend(v)
            live.update(l)
    assert not violations, (
        "time.time() in serve/ or observe/ outside the documented "
        "wall-clock-anchor allowlist (durations and deadlines use "
        "time.monotonic()/perf_counter — wall time jumps under NTP "
        f"slew; extend TIME_TIME_ALLOWLIST only for record anchors): "
        f"{violations}")
    stale = set(TIME_TIME_ALLOWLIST) - live
    assert not stale, (
        f"time.time allowlist entries match no code: {stale}")


def test_allowlist_entries_still_exist():
    """A stale allowlist entry means the site was fixed or moved —
    prune it so the list stays an honest inventory."""
    live = set()
    for f in sorted(PACKAGE.rglob("*.py")):
        rel = str(f.relative_to(PACKAGE)).replace("\\", "/")
        tree = ast.parse(f.read_text(), filename=str(f))

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if (isinstance(child, ast.ExceptHandler)
                        and _catches_broadly(child)
                        and _is_swallowing(child)):
                    live.add((rel, _enclosing_function(stack)))
                walk(child, stack + [child])

        walk(tree, [])
    stale = set(ALLOWLIST) - live
    assert not stale, f"allowlist entries no longer match any code: {stale}"


# -- serve/ per-slot exception discipline (ISSUE 8 satellite) ---------------
#
# The resilience layer's whole contract is that a fault is either
# RECOVERED (the entry is quarantined/retried/finished honestly) or
# PROPAGATED (the engine-failure cleanup aborts the batch and the error
# re-raises). An except block in serve/ that does neither — catches,
# logs-or-not, and falls through — is a request silently lost, the
# exact bug class the quarantine machinery exists to kill. This scan
# walks every handler in serve/ — serve/cluster/ included (ISSUE 12),
# which now also covers the elastic layer's autoscaler and the
# persistent compile cache (ISSUE 18): the router's handlers must
# route through ITS recovery entry point, `_fail_replica` (mark the
# replica dead + migrate its journal), the cluster-scope analogue of
# the scheduler's quarantine — and requires a `raise` or a call to one
# of the recovery entry points in the handler body, outside the
# documented allowlist.

_SERVE_RECOVERY_CALLS = {"_quarantine", "_abort_running",
                         "_fail_replica"}

# (path relative to serve/, enclosing function) -> why neither raising
# nor quarantining is correct there
SERVE_EXCEPT_ALLOWLIST = {
    ("scheduler.py", "_abort_running"):
        "the cleanup itself: release() may fail on the already-broken "
        "engine, but every in-flight slot must still be marked failed "
        "while the ORIGINAL engine error propagates to the caller",
    ("api.py", "resubmit_pending"):
        "journal recovery's documented skip: a WAL entry the rebuilt "
        "server can never serve (decommissioned tenant, shrunken "
        "t_max) is warned about and LEFT IN THE WAL for a rerun — "
        "aborting would block every other tenant's recovery",
    ("compile_cache.py", "load"):
        "the cache's best-effort contract (ISSUE 18): a blob that "
        "exists but cannot deserialize (torn write that survived a "
        "crash, foreign-toolchain collision) is EVICTED, counted as "
        "evicted_corrupt, logged, and reported as a miss — spin-up "
        "must fall back to a real compile, never die on a bad cache "
        "entry; tests/test_elastic.py pins the evict-as-miss path",
}


def _handler_recovers(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises (any Raise, including a
    translated one) or calls a recovery entry point."""
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = (node.func.attr if isinstance(node.func,
                                                 ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else None)
            if name in _SERVE_RECOVERY_CALLS:
                return True
    return False


def _scan_serve_handlers(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE / "serve")).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ExceptHandler):
                key = (rel, _enclosing_function(stack))
                if not _handler_recovers(child):
                    live.add(key)
                    if key not in SERVE_EXCEPT_ALLOWLIST:
                        violations.append((rel, child.lineno, key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


# -- single cost-extraction point (ISSUE 9 satellite) -----------------------
#
# XLA cost/memory accounting goes through ONE normalizing extraction
# point — observe.profile.program_report — which handles the backend
# quirks (list-vs-dict cost_analysis returns, absent memory_analysis)
# and degrades loudly-but-gracefully. Before this PR the parsing was
# copy-pasted across bench.py, two experiments files, and a test; this
# scan keeps the invariant from regressing: a direct
# `.cost_analysis()` / `.memory_analysis()` attribute call anywhere in
# the repo's python (package, bench.py, experiments/, tests/) outside
# the documented allowlist fails.

REPO = Path(__file__).parent.parent

_XLA_ANALYSIS_CALLS = {"cost_analysis", "memory_analysis"}

# (path relative to the repo root, enclosing function) -> why a direct
# call is correct there
COST_ANALYSIS_ALLOWLIST = {
    ("idc_models_tpu/observe/profile.py", "program_report"):
        "THE extraction point: the one site allowed to touch the raw "
        "XLA analyses, normalizing their quirks for everyone else",
}


def _scan_xla_analysis_calls(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(REPO)).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _XLA_ANALYSIS_CALLS):
                key = (rel, _enclosing_function(stack))
                live.add(key)
                if key not in COST_ANALYSIS_ALLOWLIST:
                    violations.append((rel, child.lineno,
                                       child.func.attr, key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def _xla_analysis_files():
    files = [REPO / "bench.py"]
    for sub in ("idc_models_tpu", "experiments", "tests"):
        files.extend(sorted((REPO / sub).rglob("*.py")))
    me = Path(__file__).resolve()
    return [f for f in files if f.resolve() != me]


def test_single_cost_analysis_extraction_point():
    violations, live = [], set()
    for f in _xla_analysis_files():
        v, l = _scan_xla_analysis_calls(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "direct .cost_analysis()/.memory_analysis() calls outside "
        "observe.profile.program_report (route through "
        "program_report/register_program — it normalizes backend "
        "quirks and keeps the accounting schema in one place; extend "
        "the documented COST_ANALYSIS_ALLOWLIST only for the "
        f"extraction point itself): {violations}")
    stale = set(COST_ANALYSIS_ALLOWLIST) - live
    assert not stale, (
        f"cost-analysis allowlist entries match no code: {stale}")


# ---------------------------------------------------------------------------
# ISSUE 16: DenseNet stays concat-free — `concatenate` is banned in
# models/densenet.py outside the documented parity reference. The packed
# dense blocks exist precisely because the iterated concat re-reads and
# re-writes the whole growing feature map every layer (the PR 14 MFU
# attribution measured intensity 2.0 against a ~240 ridge); a concat
# quietly reintroduced anywhere else in the model would silently undo
# the data-movement fix while every numeric test keeps passing.
# ---------------------------------------------------------------------------

CONCAT_ALLOWLIST = {
    ("idc_models_tpu/models/densenet.py", "dense_layer_concat"):
        "the block_impl=\"concat\" parity reference: the ONE place the "
        "literal concat semantics live, pinned bit-close against the "
        "packed path by tests/test_fused_conv.py and used as the "
        "bench_backbone_fused baseline",
}


def _scan_concat_calls(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(REPO)).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                f = child.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None)
                if name in ("concatenate", "concat"):
                    key = (rel, _enclosing_function(stack))
                    live.add(key)
                    if key not in CONCAT_ALLOWLIST:
                        violations.append((rel, child.lineno, name,
                                           key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_densenet_is_concat_free():
    violations, live = _scan_concat_calls(
        REPO / "idc_models_tpu" / "models" / "densenet.py")
    assert not violations, (
        "concatenate/concat calls in models/densenet.py outside the "
        "documented parity reference — dense blocks are concat-free by "
        "design (packed buffer + dynamic_update_slice; ISSUE 16); "
        "route new layers through the packed layout or extend the "
        f"documented CONCAT_ALLOWLIST: {violations}")
    stale = set(CONCAT_ALLOWLIST) - live
    assert not stale, (
        f"concat allowlist entries match no code: {stale}")


# -- ISSUE 11: no stray t_max-sized KV allocations in serve/ -------------
#
# The paged engine exists so HBM stops being reserved per slot's worst
# case; a new serve-side `zeros((..., t_max, ...))`-style KV allocation
# would quietly reintroduce the reservation the pool replaced. The scan
# flags allocation calls (zeros/ones/full/empty) whose literal shape
# tuple has rank >= 3 (KV-shaped — token-id buffers are 2-D) and
# mentions t_max anywhere inside it.

_ALLOC_CALLS = {"zeros", "ones", "full", "empty"}

# (path relative to the repo root, dotted enclosing-function path) ->
# why a t_max-sized KV allocation is correct there
TMAX_KV_ALLOWLIST = {
    ("idc_models_tpu/serve/engine.py", "_engine_fns.init_caches.mk"):
        "the CONTIGUOUS-mode constructor: per-slot [t_max] ring rows "
        "are exactly what that mode is — the paged twin "
        "(_paged_engine_fns) allocates the page pool instead",
    ("idc_models_tpu/serve/engine.py", "_drafter_fns.init_caches.mk"):
        "the learned DRAFTER's ring: the draft LM is deliberately "
        "tiny (a few-MB student), so per-slot [t_max] rows cost "
        "kilobytes per slot and keep the batched propose ONE jitted "
        "program — paging the student would buy nothing and add a "
        "second page table to every slot lifecycle op",
}


def _enclosing_path(stack) -> str:
    names = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    return ".".join(names) if names else "<module>"


def _mentions_t_max(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "t_max":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "t_max":
            return True
    return False


def _scan_tmax_kv_allocs(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(REPO)).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _ALLOC_CALLS
                    and child.args
                    and isinstance(child.args[0], ast.Tuple)
                    and len(child.args[0].elts) >= 3
                    and _mentions_t_max(child.args[0])):
                key = (rel, _enclosing_path(stack))
                live.add(key)
                if key not in TMAX_KV_ALLOWLIST:
                    violations.append((rel, child.lineno, key[1]))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_no_tmax_sized_kv_allocations_in_serve():
    violations, live = [], set()
    for f in sorted((PACKAGE / "serve").rglob("*.py")):
        v, l = _scan_tmax_kv_allocs(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "t_max-sized KV allocation in serve/ outside the contiguous-"
        "mode constructor — per-slot worst-case reservations are what "
        "paged KV removed; allocate pool pages (or extend the "
        f"documented TMAX_KV_ALLOWLIST): {violations}")
    stale = set(TMAX_KV_ALLOWLIST) - live
    assert not stale, (
        f"t_max KV allowlist entries match no code: {stale}")


# -- ISSUE 13: no O(population)-sized allocations in the population
# federated layer ------------------------------------------------------
#
# federated/population.py exists so a 10k+ virtual-client population
# trains in memory bounded by the cohort/wave; ONE population-shaped
# numpy allocation (or a list comprehension over the population range)
# silently re-materializes what the lazy design removed. The scan
# flags allocation calls (zeros/ones/full/empty/arange) and list/set/
# dict comprehensions whose arguments mention the population count —
# the names `n_population`/`population_size`, or `.size` read off
# `self`/`population`/`pop`/`.population`.

_POP_ALLOC_CALLS = {"zeros", "ones", "full", "empty", "arange"}
_POP_COUNT_NAMES = {"n_population", "population_size"}
_POP_OWNER_NAMES = {"self", "population", "pop"}

# (path relative to the repo root, dotted enclosing-function path) ->
# why an O(population) allocation is correct there
POPULATION_ALLOC_ALLOWLIST = {
    # key = the shared _enclosing_path (function names only; the
    # method lives on ClientPopulation)
    ("idc_models_tpu/federated/population.py", "all_weights"):
        "the one deliberately O(population) helper: materializes the "
        "weight vector for validating the weighted sampler's "
        "distribution on SMALL test populations — documented as never "
        "on the training path",
}


def _mentions_population_count(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _POP_COUNT_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "size":
            v = sub.value
            if isinstance(v, ast.Name) and v.id in _POP_OWNER_NAMES:
                return True
            if isinstance(v, ast.Attribute) and v.attr == "population":
                return True
    return False


def _scan_population_allocs(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(REPO)).replace("\\", "/")
    violations, live = [], set()

    def flag(node, stack, what):
        key = (rel, _enclosing_path(stack))
        live.add(key)
        if key not in POPULATION_ALLOC_ALLOWLIST:
            violations.append((rel, node.lineno, what, key[1]))

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _POP_ALLOC_CALLS
                    and any(_mentions_population_count(a)
                            for a in list(child.args)
                            + [kw.value for kw in child.keywords])):
                flag(child, stack, child.func.attr)
            if (isinstance(child, (ast.ListComp, ast.SetComp,
                                   ast.DictComp))
                    and any(_mentions_population_count(g.iter)
                            for g in child.generators)):
                flag(child, stack, "comprehension")
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_no_population_sized_allocations_in_population_layer():
    violations, live = [], set()
    for name in ("population.py", "async_fedavg.py"):
        v, l = _scan_population_allocs(
            PACKAGE / "federated" / name)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "population-count-shaped allocation in the population "
        "federated layer — virtual clients exist so memory is bounded "
        "by the cohort/wave, never the population (derive per-client "
        "state lazily from (seed, id), or extend the documented "
        f"POPULATION_ALLOC_ALLOWLIST): {violations}")
    stale = set(POPULATION_ALLOC_ALLOWLIST) - live
    assert not stale, (
        f"population-alloc allowlist entries match no code: {stale}")


def test_serve_handlers_quarantine_or_reraise():
    violations, live = [], set()
    for f in sorted((PACKAGE / "serve").rglob("*.py")):
        v, l = _scan_serve_handlers(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "serve/ except blocks that neither re-raise nor quarantine — a "
        "caught fault must recover the request or propagate to the "
        "engine-failure cleanup, never vanish (extend the documented "
        f"SERVE_EXCEPT_ALLOWLIST only for cleanup-path sites): "
        f"{violations}")
    stale = set(SERVE_EXCEPT_ALLOWLIST) - live
    assert not stale, (
        f"serve except allowlist entries match no code: {stale}")


# -- ISSUE 14: multi-tenant discipline ----------------------------------
#
# 1. Every TENANT-FACING metric registration must carry the `tenant`
#    label: an unlabeled "serve_tenant_*" series would aggregate every
#    tenant into one number — exactly the blindness the tenancy layer
#    exists to remove — and a dashboard built on it could never answer
#    "WHICH tenant is burning".
# 2. Cross-tenant state reads inside serve/tenancy.py are banned
#    outside a documented allowlist: the isolation story is only
#    auditable if every method provably touches ONE tenant's state,
#    with the few legitimately-global sites (registration, the stacked
#    adapter build, fleet rollups) enumerated and explained.

def _scan_tenant_metric_labels(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    violations = []

    def has_tenant_label(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg != "labels":
                continue
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                return any(isinstance(e, ast.Constant)
                           and e.value == "tenant"
                           for e in kw.value.elts)
        return False

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _METRIC_FACTORIES
                    and child.args
                    and isinstance(child.args[0], ast.Constant)
                    and isinstance(child.args[0].value, str)
                    and "tenant" in child.args[0].value
                    and not has_tenant_label(child)):
                violations.append((rel, child.lineno,
                                   child.args[0].value))
            walk(child)

    walk(tree)
    return violations


def test_tenant_metric_registrations_carry_tenant_label():
    violations = []
    for f in sorted(PACKAGE.rglob("*.py")):
        if f.name == "metrics_registry.py":
            continue      # the factory definitions, not registrations
        violations.extend(_scan_tenant_metric_labels(f))
    assert not violations, (
        "tenant-facing metric registered WITHOUT the tenant label — an "
        "unlabeled serve_tenant_* series aggregates every tenant into "
        "one number, which can never answer 'which tenant is burning': "
        f"{violations}")


# function name in serve/tenancy.py -> why it legitimately sees every
# tenant (anything NOT here must read exactly one tenant's state)
TENANCY_CROSS_TENANT_ALLOWLIST = {
    "register": "duplicate-name check is the identity contract",
    "_check_adapter": "shape agreement is a property OF the set — one "
                      "[V, r] across every tenant's adapter",
    "build": "the one freeze point: stacks every adapter into the "
             "gather table, declares every SLO, builds every brownout",
    "names": "the documented fleet-rollup accessor (registration "
             "order = tid order)",
    "n_tenants": "set SIZE only — reads no tenant's state",
}

_TENANT_MAPS = {"_tenants", "brownouts"}


def _scan_cross_tenant_reads(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    violations, live = [], set()

    def names_tenant_map(node) -> bool:
        # self._tenants / self.brownouts, or a .values()/.items()/
        # .keys() view over them
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("values", "items", "keys")):
            node = node.func.value
        return (isinstance(node, ast.Attribute)
                and node.attr in _TENANT_MAPS)

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            iter_sites = []
            if isinstance(child, (ast.For, ast.comprehension)):
                iter_sites.append(child.iter)
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id in ("list", "sorted", "len",
                                          "dict", "set", "tuple",
                                          "any", "all")):
                iter_sites.extend(child.args)
            for site in iter_sites:
                if not names_tenant_map(site):
                    continue
                fn = _enclosing_function(stack)
                live.add(fn)
                if fn not in TENANCY_CROSS_TENANT_ALLOWLIST:
                    violations.append(
                        (fn, getattr(child, "lineno",
                                     getattr(site, "lineno", 0))))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_no_cross_tenant_reads_in_tenancy():
    violations, live = _scan_cross_tenant_reads(
        PACKAGE / "serve" / "tenancy.py")
    assert not violations, (
        "cross-tenant state read in serve/tenancy.py outside the "
        "documented allowlist — tenancy methods must read ONE "
        "tenant's state so the isolation story stays auditable "
        "(extend TENANCY_CROSS_TENANT_ALLOWLIST only for genuinely "
        f"set-level operations, with the why): {violations}")
    stale = set(TENANCY_CROSS_TENANT_ALLOWLIST) - live
    assert not stale, (
        f"tenancy cross-tenant allowlist entries match no code: "
        f"{stale}")


# -- ISSUE 15: one sharding-resolution layer ----------------------------
#
# Placement policy lives in partition.py (regex->PartitionSpec rules)
# and the mesh/tp helpers; before this PR ten files constructed
# `NamedSharding(` / `PartitionSpec(` ad hoc, which is exactly how
# subsystems drift apart (the serve engine's trailing-None recompile
# was one symptom). The scan resolves `from jax.sharding import ...`
# aliases (including `PartitionSpec as P`) plus attribute-form
# `jax.sharding.X(` calls, and fails on any construction outside the
# documented allowlist. shard_map in/out specs are fold INTERNALS —
# per-device views of one program, not placement policy — so the
# explicit-collective files are allowlisted as such.

_SHARDING_CTORS = {"NamedSharding", "PartitionSpec"}

# relative path -> why constructing sharding objects there is correct
SHARDING_CTOR_ALLOWLIST = {
    "partition.py":
        "THE rule->spec resolution layer: adapts rule specs to leaf "
        "shapes/meshes and builds the resolved NamedShardings",
    "mesh.py":
        "the axis-aware construction helpers (sharding, replicated, "
        "batch_seq_spec/batch_seq_sharding) every other file calls",
    "tp.py":
        "the channel rule's readable shape-form (channel_spec) and "
        "its rules instance",
    "models/registry.py":
        "the per-model DEFAULT rule sets: rule definitions are "
        "(regex, PartitionSpec) pairs by construction",
    "ring_decode.py":
        "ring fold internals: shard_map per-device specs and the "
        "cache/pool layouts the folds are written against",
    "federated/fedavg.py":
        "explicit-collective shard_map in/out specs of the round "
        "program (client-axis fold internals)",
    "federated/population.py":
        "explicit-collective shard_map specs of the streamed wave "
        "program (client-axis fold internals)",
    "secure/fedavg.py":
        "explicit-collective shard_map specs of the secure-masking "
        "round (client-axis fold internals)",
}


def _scan_sharding_ctors(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(PACKAGE)).replace("\\", "/")
    aliases = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom)
                and node.module == "jax.sharding"):
            for a in node.names:
                if a.name in _SHARDING_CTORS:
                    aliases[a.asname or a.name] = a.name
    violations, live = [], set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        ctor = None
        if isinstance(fn, ast.Name) and fn.id in aliases:
            ctor = aliases[fn.id]
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _SHARDING_CTORS):
            ctor = fn.attr
        if ctor is None:
            continue
        live.add(rel)
        if rel not in SHARDING_CTOR_ALLOWLIST:
            violations.append((rel, node.lineno, ctor))
    return violations, live


def test_sharding_construction_single_layer():
    violations, live = [], set()
    for f in sorted(PACKAGE.rglob("*.py")):
        v, l = _scan_sharding_ctors(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "NamedSharding(/PartitionSpec( constructed outside the "
        "sharding layers — resolve placement through "
        "partition.PartitionRules (models/registry.py holds the "
        "per-model defaults) or the mesh.py helpers; extend the "
        "documented SHARDING_CTOR_ALLOWLIST only for fold-internal "
        f"shard_map specs: {violations}")
    stale = set(SHARDING_CTOR_ALLOWLIST) - live
    assert not stale, (
        f"sharding-constructor allowlist entries match no code: "
        f"{stale}")


# -- ISSUE 17: every checkpoint byte goes through an atomic commit ------
#
# checkpoint/sharded.py's completion contract (shard sha256s + a
# MANIFEST.json committed last) only holds if NO code path writes into
# a checkpoint directory around the tmp-then-`os.replace` commit
# helpers. A raw `open(..., "w")`, `np.save`, `Path.write_text`, or
# `shutil.copy*` under the checkpoint modules would be a torn-write
# hole the manifest cannot see. The scan walks the checkpoint-owning
# files and flags every write-capable call outside the documented
# atomic-commit allowlist.

_CKPT_FILES = (
    "idc_models_tpu/checkpoint/sharded.py",
    "idc_models_tpu/checkpoint/rollout.py",
    "idc_models_tpu/checkpoint/__init__.py",
    "idc_models_tpu/train/checkpoint.py",
)

# np.save/np.savez/np.savetxt and shutil's content-copying entry points
_RAW_WRITE_ATTRS = {"save", "savez", "savez_compressed", "savetxt",
                    "copy", "copy2", "copyfile", "copytree", "move",
                    "write_text", "write_bytes", "touch"}

# (repo-relative path, dotted enclosing-function path) -> why the raw
# write IS the atomic commit (or happens strictly before one)
CKPT_WRITE_ALLOWLIST = {
    ("idc_models_tpu/checkpoint/sharded.py", "_write_bytes"):
        "THE atomic byte commit: tmp-suffixed open('wb') + fsync + "
        "os.replace — every other writer (shards, fragments, manifest "
        "via _commit_json) funnels through here",
    ("idc_models_tpu/train/checkpoint.py", "save_checkpoint"):
        "digest write_text + marker touch land in <path>.tmp BEFORE "
        "the os.replace rename commit publishes the directory — a "
        "crash leaves a markerless partial checkpoint_exists refuses",
}


def _is_write_open(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str):
        # no literal mode = default "r"; a computed mode is opaque —
        # flag it so the writer documents an allowlist entry
        return len(call.args) >= 2 or any(k.arg == "mode"
                                          for k in call.keywords)
    return any(c in mode for c in "wax+")


def _scan_ckpt_writes(path: Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    rel = str(path.relative_to(REPO)).replace("\\", "/")
    violations, live = [], set()

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            hit = None
            if isinstance(child, ast.Call):
                if _is_write_open(child):
                    hit = "open(w)"
                elif (isinstance(child.func, ast.Attribute)
                      and child.func.attr in _RAW_WRITE_ATTRS):
                    hit = child.func.attr
            if hit is not None:
                key = (rel, _enclosing_path(stack))
                live.add(key)
                if key not in CKPT_WRITE_ALLOWLIST:
                    violations.append((rel, child.lineno, hit))
            walk(child, stack + [child])

    walk(tree, [])
    return violations, live


def test_checkpoint_writes_only_through_atomic_commit():
    violations, live = [], set()
    for rel in _CKPT_FILES:
        f = REPO / rel
        if not f.exists():
            continue
        v, l = _scan_ckpt_writes(f)
        violations.extend(v)
        live.update(l)
    assert not violations, (
        "raw write under the checkpoint modules outside the atomic-"
        "commit helpers — a byte that skips tmp-then-os.replace is a "
        "torn-write hole the manifest/marker contract cannot see; "
        "route it through checkpoint.sharded._write_bytes/_commit_json "
        "(or extend the documented CKPT_WRITE_ALLOWLIST): "
        f"{violations}")
    stale = set(CKPT_WRITE_ALLOWLIST) - live
    assert not stale, (
        f"checkpoint write allowlist entries match no code: {stale}")


# -- serve --drafter registry lockstep ----------------------------------
#
# cli.SERVE_DRAFTERS maps each `serve --drafter` choice to the class
# implementing it. Drift in either direction is a silent failure: a
# table entry naming a class without `propose` dies deep inside the
# scheduler on the first speculative cycle, and a drafter class added
# to models/ but left out of the table simply cannot be reached from
# the CLI. Classes implementing the contract for composition or
# testing only (deliberately NOT CLI-selectable) document themselves
# here — each entry says why.
DRAFTER_TABLE_EXEMPT = {
    # none today: every propose-bearing class under models/draft*.py
    # is CLI-reachable
}

_DRAFTER_FILES = ("models/draft.py", "models/draft_lm.py")


def _propose_bearing_classes():
    found = set()
    for rel in _DRAFTER_FILES:
        tree = ast.parse((PACKAGE / rel).read_text(), filename=rel)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and any(
                    isinstance(b, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))
                    and b.name == "propose" for b in node.body):
                found.add(node.name)
    return found


def test_serve_drafter_table_entries_implement_the_contract():
    import importlib

    from idc_models_tpu.cli import SERVE_DRAFTERS

    for name, (module, cls_name, story) in SERVE_DRAFTERS.items():
        cls = getattr(importlib.import_module(module), cls_name)
        assert callable(getattr(cls, "propose", None)), (
            f"--drafter {name} maps to {module}.{cls_name}, which "
            f"has no propose(): every SERVE_DRAFTERS entry must "
            f"implement the models/draft.py contract")
        assert story, f"--drafter {name} carries no help story"


def test_every_drafter_class_is_cli_reachable_or_exempt():
    from idc_models_tpu.cli import SERVE_DRAFTERS

    listed = {cls for _mod, cls, _story in SERVE_DRAFTERS.values()}
    bearing = _propose_bearing_classes()
    orphans = bearing - listed - set(DRAFTER_TABLE_EXEMPT)
    assert not orphans, (
        "drafter class defines propose() but is reachable from "
        "neither `serve --drafter` (cli.SERVE_DRAFTERS) nor the "
        "documented DRAFTER_TABLE_EXEMPT — wire it into the table or "
        f"document why it is composition-only: {sorted(orphans)}")
    stale = set(DRAFTER_TABLE_EXEMPT) - bearing
    assert not stale, (
        f"drafter exemptions match no propose-bearing class: "
        f"{sorted(stale)}")


def test_drafter_argparse_choices_stay_in_lockstep():
    """The `--drafter` choices expression must be DERIVED from
    SERVE_DRAFTERS (not a hand-written list), so adding a table entry
    automatically surfaces it in argparse — and vice versa a choices
    edit without a table entry is impossible."""
    tree = ast.parse((PACKAGE / "cli.py").read_text(),
                     filename="cli.py")
    hit = None
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "--drafter"):
            hit = node
    assert hit is not None, "serve grew no --drafter flag"
    choices = next((kw.value for kw in hit.keywords
                    if kw.arg == "choices"), None)
    assert choices is not None, "--drafter has no choices= keyword"
    names = {n.id for n in ast.walk(choices)
             if isinstance(n, ast.Name)}
    assert "SERVE_DRAFTERS" in names, (
        "--drafter choices are hand-written instead of derived from "
        "SERVE_DRAFTERS — the two will drift")
