"""Unit tests for the explicit-pytree layer library."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models import core, small_cnn


def test_dense_shapes():
    m = core.dense(16, 4)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((2, 16)))
    assert y.shape == (2, 4)


def test_conv_shapes_and_stride():
    m = core.conv2d(3, 8, 3, stride=2, padding="SAME")
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((2, 10, 10, 3)))
    assert y.shape == (2, 5, 5, 8)


def test_conv_channel_pad_is_exact():
    """The MXU stem-conv optimization (input+kernel zero-padded 3 -> 4
    channels, see core.conv2d) must be arithmetically invisible: same
    output as the direct 3-channel convolution, and gradients land only
    on the real (kh, kw, 3, out) kernel."""
    from jax import lax

    m = core.conv2d(3, 8, 3, padding="SAME")
    v = m.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 10, 10, 3))
    y, _ = m.apply(v.params, v.state, x)
    direct = lax.conv_general_dilated(
        x, v.params["kernel"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + v.params["bias"]
    np.testing.assert_array_equal(np.asarray(y), np.asarray(direct))
    assert v.params["kernel"].shape == (3, 3, 3, 8)  # Keras-parity params

    def loss(params):
        out, _ = m.apply(params, v.state, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(v.params)
    assert g["kernel"].shape == (3, 3, 3, 8)
    assert bool(jnp.all(jnp.isfinite(g["kernel"])))


def test_depthwise_conv():
    m = core.depthwise_conv2d(6, 3)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((2, 8, 8, 6)))
    assert y.shape == (2, 8, 8, 6)
    assert v.params["kernel"].shape == (3, 3, 1, 6)


@pytest.mark.parametrize("stride,size", [(1, 8), (1, 7), (2, 8), (2, 7),
                                         (2, 25)])
def test_depthwise_taps_matches_grouped(stride, size):
    """impl='taps' (explicit shifted elementwise MAC) is the same math as
    XLA's grouped-conv lowering — SAME padding, both strides, odd/even
    spatial (25 = the MobileNet 50x50 post-stem resolution)."""
    grouped = core.depthwise_conv2d(6, 3, stride=stride)
    taps = core.depthwise_conv2d(6, 3, stride=stride, impl="taps")
    v = grouped.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, size, size, 6))
    yg, _ = grouped.apply(v.params, v.state, x)
    yt, _ = taps.apply(v.params, v.state, x)
    assert yg.shape == yt.shape
    np.testing.assert_allclose(np.asarray(yt), np.asarray(yg),
                               rtol=1e-5, atol=1e-6)


def test_depthwise_taps_rejections():
    with pytest.raises(ValueError, match="grouped|taps"):
        core.depthwise_conv2d(6, 3, impl="im2col")
    with pytest.raises(ValueError, match="SAME"):
        core.depthwise_conv2d(6, 3, impl="taps", padding="VALID")


def test_batch_norm_train_vs_eval():
    m = core.batch_norm(4, momentum=0.5)
    v = m.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (32, 4)) * 3 + 1
    y, new_state = m.apply(v.params, v.state, x, train=True)
    # normalized output: ~zero mean, ~unit var
    np.testing.assert_allclose(np.mean(y, 0), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.std(y, 0), 1.0, atol=1e-2)
    # moving stats moved toward batch stats
    assert not np.allclose(new_state["mean"], v.state["mean"])
    # eval mode uses stored stats and does not update them
    y2, s2 = m.apply(v.params, new_state, x, train=False)
    assert s2 is new_state


def test_maxpool_matches_numpy():
    m = core.max_pool(2)
    v = m.init(jax.random.key(0))
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = m.apply(v.params, v.state, x)
    expect = np.array([[5, 7], [13, 15]], np.float32).reshape(1, 2, 2, 1)
    np.testing.assert_array_equal(np.asarray(y), expect)


def test_dropout_train_eval():
    m = core.dropout(0.5)
    v = m.init(jax.random.key(0))
    x = jnp.ones((4, 100))
    y_eval, _ = m.apply(v.params, v.state, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_tr, _ = m.apply(v.params, v.state, x, train=True, rng=jax.random.key(1))
    zeros = float(jnp.mean(y_tr == 0))
    assert 0.3 < zeros < 0.7
    # surviving entries are scaled by 1/keep
    assert float(jnp.max(y_tr)) == 2.0


def test_summary_counts_and_freeze_annotations():
    """core.summary: Keras-style table with exact totals; the fine-tune
    mask's trainable split matches the Keras arithmetic (block5 convs
    3x(3*3*512*512+512) + head 513 = 7,079,937)."""
    from idc_models_tpu.models.vgg import fine_tune_mask, vgg16

    s = core.summary(small_cnn(10, 3, 1))
    assert "Total params: 1,937" in s
    assert "conv1" in s and "kernel[3, 3, 3, 32]" in s

    model = vgg16(1)
    variables = model.init(jax.random.key(0))
    s = core.summary(model, variables,
                     trainable_mask=fine_tune_mask(variables.params, 15))
    assert "Total params: 14,715,201" in s      # pinned vs Keras
    assert "Trainable params: 7,079,937" in s
    assert "Non-trainable params: 7,635,264" in s
    assert "(frozen)" in s
    # layer_names order: block1 before block5 before head
    lines = s.splitlines()
    idx = {name: next(i for i, ln in enumerate(lines)
                      if ln.split() and ln.split()[0].endswith(name))
           for name in ("block1_conv1", "block5_conv3", "head")}
    assert idx["block1_conv1"] < idx["block5_conv3"] < idx["head"]


def test_small_cnn_forward_and_param_count():
    m = small_cnn(10, 3, 1)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((5, 10, 10, 3)),
                   train=True, rng=jax.random.key(1))
    assert y.shape == (5, 1)
    # conv: 3*3*3*32+32 = 896 ; fc1: (2*2*32)*8+8 = 1032 ; head: 8+1 = 9
    assert core.count_params(v.params) == 896 + 1032 + 9


def test_trainability_mask():
    m = small_cnn(10, 3, 1)
    v = m.init(jax.random.key(0))
    mask = core.trainability_mask(v.params, lambda path: path[0] == "head")
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    for path, val in flat:
        keys = tuple(p.key for p in path)
        assert val == (keys[0] == "head")


def test_batch_norm_frozen_ignores_train_flag():
    m = core.batch_norm(4, frozen=True)
    v = m.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (16, 4)) * 3 + 2
    y, new_state = m.apply(v.params, v.state, x, train=True)
    # inference mode: stats unchanged, normalization uses stored (0,1)
    np.testing.assert_array_equal(np.asarray(new_state["mean"]),
                                  np.asarray(v.state["mean"]))
    eps = 1e-3
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x) / np.sqrt(1 + eps), rtol=1e-5)


def test_conv2d_explicit_padding():
    m = core.conv2d(1, 1, 7, stride=2, padding=((3, 3), (3, 3)),
                    use_bias=False)
    v = m.init(jax.random.key(0))
    y, _ = m.apply(v.params, v.state, jnp.ones((1, 224, 224, 1)))
    assert y.shape == (1, 112, 112, 1)
