"""ISSUE 18: the elastic cluster — autoscaling from health documents
(serve/cluster/autoscaler.py), warm replica spin-up through the
persistent compile cache (serve/compile_cache.py), and graceful drain
with live mid-decode slot migration — against its hard contracts:

1. POLICY — `decide()` is pure over (healths, now, state, cfg): dwell
   hysteresis, post-action cooldown, and min/max bounds all replay
   deterministically from a fake clock; holds are silent.
2. WARM SPIN-UP — the compile cache round-trips an AOT-serialized
   executable; a corrupt blob is evicted and reported as a miss (never
   a crash); any toolchain/config drift changes the key; a second
   replica built against a populated cache deserializes instead of
   compiling.
3. MIGRATION — draining with migrate=True moves a MID-DECODE request's
   slot (KV rows + RNG key-data + emitted tokens) onto a peer and the
   final output is bit-identical to an unmigrated run, greedy and
   sampled; with no free peer slot it falls back to journal-style
   from-the-prompt re-placement, still bit-identical; a crash in the
   export->import gap loses nothing — the source WAL still holds the
   request and replay reproduces it exactly.
4. HONESTY — with every decode replica draining or dead, submit()
   returns the terminal shed Result naming the condition instead of
   queueing into a fleet that will never run it; add_replica revives
   the cluster and the same request then succeeds.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import (
    AutoscaleConfig, Autoscaler, CompileCache, Request, Router,
    build_replica,
)
from idc_models_tpu.serve.cluster import autoscaler as asc

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _model_kw():
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ)


def _replica(params, rid, *, device=None, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    return build_replica(params, replica_id=rid, device=device,
                         **_model_kw(), **kw)


def _serial_tokens(params, prompt, steps):
    gen = Generator(params, mesh=None, cache_dtype=jnp.float32,
                    **_model_kw())
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps)
    return toks.tolist()[0]


def _health(qd=0, load=0, *, shedding=False, burning=False,
            pages=(None, None), state="live", role="mixed"):
    return {"state": state, "role": role, "queue_depth": qd,
            "load": load, "shedding": shedding, "slo_breached": burning,
            "kv_pages_total": pages[0], "kv_pages_used": pages[1]}


# -- 1. autoscaling policy --------------------------------------------------


def test_autoscale_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscaleConfig(queue_low=4.0, queue_high=4.0)
    with pytest.raises(ValueError, match="page_headroom"):
        AutoscaleConfig(page_headroom=1.0)
    with pytest.raises(ValueError, match="dwell_s"):
        AutoscaleConfig(dwell_s=-1.0)


def test_autoscale_dwell_gates_the_up_signal():
    """One bursty tick never buys a replica: the up signal must HOLD
    for dwell_s, and quiet in between resets the clock."""
    cfg = AutoscaleConfig(queue_high=4.0, dwell_s=1.0, cooldown_s=0.0)
    hot = [_health(qd=10)]
    a, _, st = asc.decide(hot, now=0.0, cfg=cfg)
    assert a == "hold"                     # signal just appeared
    a, _, st = asc.decide(hot, now=0.5, state=st, cfg=cfg)
    assert a == "hold"                     # held 0.5 < dwell 1.0
    # a quiet tick resets the dwell clock...
    a, _, st = asc.decide([_health(qd=2)], now=0.8, state=st, cfg=cfg)
    assert a == "hold" and st["up_since"] is None
    # ...so the signal must re-earn the full dwell
    a, _, st = asc.decide(hot, now=1.0, state=st, cfg=cfg)
    assert a == "hold"
    a, reason, st = asc.decide(hot, now=2.1, state=st, cfg=cfg)
    assert a == "up" and "queue_high" in reason


def test_autoscale_cooldown_prevents_staircasing():
    """After an action the policy is quiet for cooldown_s even though
    the raw signal persists through spin-up — without this the fleet
    staircases straight to max."""
    cfg = AutoscaleConfig(queue_high=4.0, dwell_s=0.0, cooldown_s=5.0)
    hot = [_health(qd=10)]
    a, _, st = asc.decide(hot, now=0.0, cfg=cfg)
    assert a == "up"
    a, reason, st = asc.decide(hot, now=2.0, state=st, cfg=cfg)
    assert (a, reason) == ("hold", "cooldown")
    a, _, st = asc.decide(hot, now=5.5, state=st, cfg=cfg)
    assert a == "up"                       # cooldown elapsed


def test_autoscale_bounds_and_down_signal():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=2,
                          queue_low=1.0, queue_high=4.0,
                          dwell_s=0.0, cooldown_s=0.0)
    # at max: the up signal reports the bound instead of firing
    a, reason, _ = asc.decide([_health(qd=10), _health(qd=10)],
                              now=0.0, cfg=cfg)
    assert a == "hold" and "max_replicas" in reason
    # two idle replicas above min: down fires
    a, reason, _ = asc.decide([_health(qd=0), _health(qd=0)],
                              now=0.0, cfg=cfg)
    assert a == "down" and "queue_low" in reason
    # at min: never below the floor
    a, _, _ = asc.decide([_health(qd=0)], now=0.0, cfg=cfg)
    assert a == "hold"


def test_autoscale_down_blocked_by_shed_or_burn():
    """An idle-looking queue does not license scale-down while any
    replica sheds or burns its SLO — load is hiding, not absent."""
    cfg = AutoscaleConfig(dwell_s=0.0, cooldown_s=0.0)
    for sick in (_health(qd=0, shedding=True),
                 _health(qd=0, burning=True)):
        a, _, _ = asc.decide([_health(qd=0), sick], now=0.0, cfg=cfg)
        assert a != "down"                 # shedding even argues UP
    # shedding is itself an UP signal regardless of queue depth
    a, reason, _ = asc.decide([_health(qd=0, shedding=True)],
                              now=0.0, cfg=cfg)
    assert a == "up" and "shedding" in reason


def test_autoscale_page_headroom_and_liveness_filters():
    cfg = AutoscaleConfig(page_headroom=0.2, dwell_s=0.0,
                          cooldown_s=0.0)
    a, reason, _ = asc.decide([_health(qd=0, pages=(100, 95))],
                              now=0.0, cfg=cfg)
    assert a == "up" and "headroom" in reason
    # draining/dead/prefill replicas neither vote nor count as capacity
    fleet = [_health(qd=50, state="draining"),
             _health(qd=50, state="dead"),
             _health(qd=50, role="prefill")]
    a, reason, st = asc.decide(fleet, now=0.0, cfg=cfg)
    assert (a, reason) == ("hold", "no live decode replica")
    assert st == asc._fresh_state()


def test_autoscaler_wrapper_records_actions_only():
    auto = Autoscaler(AutoscaleConfig(dwell_s=0.0, cooldown_s=0.0))
    assert auto.evaluate([_health(qd=2)], now=0.0) is None   # hold
    rec = auto.evaluate([_health(qd=10)], now=1.0)
    assert rec is not None and rec["action"] == "up"
    assert rec["live"] == 1 and rec["t"] == 1.0
    assert [d["action"] for d in auto.decisions] == ["up"]


# -- 2. compile cache + warm spin-up ----------------------------------------


def test_compile_cache_roundtrip_and_key_drift(tmp_path):
    """Store an AOT-compiled executable, reopen the cache cold, load
    it back, and run BOTH: identical outputs. Any drift in program
    name or fingerprint is a different key."""
    f = jax.jit(lambda x: x * 2 + 1)
    lowered = f.lower(jnp.zeros((4,), jnp.float32))
    cc = CompileCache(tmp_path)
    key = cc.key(program="probe", fingerprint={"embed": E})
    assert cc.load(key) is None and cc.misses == 1
    exe = cc.compile_and_store(key, lowered)
    assert cc.stores == 1 and cc.compile_s > 0
    # a fresh instance (the "new process") deserializes the same key
    cc2 = CompileCache(tmp_path)
    warm = cc2.load(key)
    assert warm is not None
    assert cc2.summary()["hits"] == 1 and cc2.deserialize_s > 0
    x = jnp.arange(4, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(exe(x)),
                                  np.asarray(warm(x)))
    # invalidation IS the key: program or fingerprint drift never
    # collides with the stored entry
    assert cc.key(program="other", fingerprint={"embed": E}) != key
    assert cc.key(program="probe", fingerprint={"embed": E + 1}) != key


def test_compile_cache_corrupt_blob_evicted_as_miss(tmp_path):
    """A torn/foreign blob under a valid key is evicted and counted
    as a miss — spin-up falls back to a real compile, never dies on a
    bad cache entry, and the rebuilt entry replaces it."""
    cc = CompileCache(tmp_path)
    key = cc.key(program="probe", fingerprint={})
    blob = cc._file(key)
    blob.write_bytes(b"not a serialized executable")
    assert cc.load(key) is None
    assert cc.evicted_corrupt == 1 and cc.misses == 1
    assert not blob.exists()               # evicted, not left to rot
    f = jax.jit(lambda x: x + 1)
    cc.compile_and_store(key, f.lower(jnp.zeros((2,), jnp.float32)))
    assert CompileCache(tmp_path).load(key) is not None


def test_warm_replica_spinup_hits_cache(params, tmp_path):
    """The ISSUE's warm spin-up contract at the replica surface: the
    first build compiles and stores, a second replica against the same
    populated cache deserializes (hits > 0, zero new stores) and still
    serves bit-identically."""
    cache = CompileCache(tmp_path / "cc")
    r0 = _replica(params, "r0", compile_cache=cache)
    assert cache.stores > 0 and cache.hits == 0
    stored = cache.stores
    r1 = _replica(params, "r1", compile_cache=cache)
    assert cache.hits > 0, "warm spin-up must deserialize, not compile"
    assert cache.stores == stored
    router = Router([r0, r1])
    q = Request(id="warm", prompt=(1, 2, 3, 4), max_new_tokens=6)
    out = router.run([(0.0, q)])
    assert out[0].status == "ok"
    assert out[0].tokens == _serial_tokens(params, q.prompt, 6)
    router.close()


# -- 3. live slot migration -------------------------------------------------


def _journal_events(path):
    out = []
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        out.append((rec.get("event"), rec.get("id"),
                    rec.get("status"), rec.get("direction")))
    return out


def test_drain_migrates_live_slots_bit_identical(devices, params,
                                                 tmp_path):
    """The tentpole drill: two requests mid-decode on two replicas,
    drain r0 with migrate=True. r0's request moves IN ITS SLOT (KV +
    RNG + emitted tokens) onto r1 and finishes there with output
    bit-identical to the serial oracle; both WALs carry the gap
    protocol (out+migrated on the source, submit+in+ok on the
    target)."""
    reps = [_replica(params, f"r{i}", device=devices[i],
                     journal_path=str(tmp_path / f"j{i}.jsonl"))
            for i in range(2)]
    router = Router(reps)
    rng = np.random.default_rng(3)
    reqs = [Request(id=f"m{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + i)),
                    max_new_tokens=12)
            for i in range(2)]
    for q in reqs:
        assert router.submit(q)
    assert router._owner["m0"].replica_id == "r0"
    router.step()                          # both now MID-decode
    moved = router.drain_replica("r0", migrate=True)
    assert "m0" in moved
    assert [m["rid"] for m in router.slot_migrations] == ["m0"]
    assert router.slot_migrations[0]["to"] == "r1"
    router.drain()
    for q in reqs:
        got = router.poll(q.id)
        assert got is not None and got.status == "ok", (q.id, got)
        assert got.tokens == _serial_tokens(params, q.prompt, 12), q.id
    assert router.summary()["cluster_slot_migrations"] == 1
    src = _journal_events(tmp_path / "j0.jsonl")
    tgt = _journal_events(tmp_path / "j1.jsonl")
    assert ("journal_migrate", "m0", None, "out") in src
    assert ("journal_finish", "m0", "migrated", None) in src
    assert ("journal_submit", "m0", None, None) in tgt
    assert ("journal_migrate", "m0", None, "in") in tgt
    assert ("journal_finish", "m0", "ok", None) in tgt


def test_sampled_migration_carries_rng_bit_identical(devices, params):
    """Sampled decode across a migration: the request's raw threefry
    key-data rides the slot move, so the migrated run reproduces the
    unmigrated run bit for bit even though it lands in a DIFFERENT
    slot index on the peer."""
    def fleet():
        return [_replica(params, f"r{i}", device=devices[i],
                         temperature=1.0)
                for i in range(2)]

    q = Request(id="s0", prompt=(1, 2, 3, 4, 5), max_new_tokens=10,
                seed=42)
    peer_load = Request(id="s1", prompt=(6, 7, 8), max_new_tokens=10,
                        seed=7)
    # oracle: the same pair, same placement, NO migration
    r_static = Router(fleet())
    for p in (q, peer_load):
        assert r_static.submit(p)
    r_static.drain()
    want = r_static.poll("s0").tokens
    r_static.close()

    r_mig = Router(fleet())
    for p in (q, peer_load):
        assert r_mig.submit(p)
    r_mig.step()
    moved = r_mig.drain_replica("r0", migrate=True)
    assert "s0" in moved and r_mig.slot_migrations
    r_mig.drain()
    got = r_mig.poll("s0")
    assert got.status == "ok" and got.tokens == want
    r_mig.close()


def test_migration_falls_back_when_no_free_slot(devices, params):
    """With every peer slot occupied, drain migrate=True falls back to
    journal-style from-the-prompt re-placement — slower, still
    bit-identical, and the rollup tells the two modes apart."""
    reps = [_replica(params, f"r{i}", device=devices[i], n_slots=1)
            for i in range(2)]
    router = Router(reps)
    reqs = [Request(id=f"f{i}", prompt=(1 + i, 2 + i, 3 + i),
                    max_new_tokens=10)
            for i in range(2)]
    for q in reqs:
        assert router.submit(q)
    router.step()                          # r1's only slot is busy
    moved = router.drain_replica("r0", migrate=True)
    assert "f0" in moved
    assert router.slot_migrations == []    # no seat -> no slot move
    router.drain()
    for q in reqs:
        got = router.poll(q.id)
        assert got.status == "ok"
        assert got.tokens == _serial_tokens(params, q.prompt, 10), q.id
    s = router.summary()
    assert s["cluster_slot_migrations"] == 0
    assert s["cluster_migrations"] >= 1    # the fallback path


def test_crash_in_export_import_gap_loses_nothing(devices, params,
                                                  tmp_path):
    """The gap protocol: the source WAL keeps the request OPEN until
    the import lands. Killing the source after export_running but
    before any import leaves the WAL's pending set intact, and the
    journal failover replays the request from the prompt,
    bit-identically."""
    reps = [_replica(params, f"r{i}", device=devices[i],
                     journal_path=str(tmp_path / f"j{i}.jsonl"))
            for i in range(2)]
    router = Router(reps)
    q = Request(id="gap0", prompt=(1, 2, 3, 4), max_new_tokens=10)
    assert router.submit(q)
    assert router._owner["gap0"].replica_id == "r0"
    router.step()
    # reach into the drain protocol mid-flight: quiesce, then export —
    # and then the source dies before anyone imports
    src = reps[0].server
    src.quiesce()
    src.scheduler.begin_drain()
    entry, snap = src.scheduler.export_running("gap0")
    assert entry.rid == "gap0" and snap is not None
    migrated = router.kill_replica("r0")
    assert "gap0" in migrated              # WAL still held it open
    router.drain()
    got = router.poll("gap0")
    assert got is not None and got.status == "ok"
    assert got.tokens == _serial_tokens(params, q.prompt, 10)
    # the dead source's WAL must NOT claim the request finished
    src_events = _journal_events(tmp_path / "j0.jsonl")
    assert not any(e == "journal_finish" and r == "gap0"
                   for e, r, _, _ in src_events)


# -- 4. all-draining honesty + revival --------------------------------------


def test_all_draining_sheds_honestly_then_add_replica_revives(
        devices, params):
    """Every decode replica draining => submit() answers with the
    terminal shed Result naming the condition (not a queue into a
    fleet that will never run it). add_replica revives the cluster
    and the SAME request then succeeds."""
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps)
    for rid in ("r0", "r1"):
        router.drain_replica(rid, wait=True)
    q = Request(id="orphan", prompt=(1, 2, 3), max_new_tokens=4)
    assert router.submit(q) is False
    got = router.poll("orphan")
    assert got is not None and got.status == "shed"
    assert "no live decode-capable replica" in got.error
    assert router.summary()["cluster_shed"] >= 1
    # revival: a fresh replica joins and the same request now runs
    router.add_replica(_replica(params, "r2"))
    assert router.summary()["cluster_replicas_live"] == 1
    assert router.submit(q)
    router.drain()
    final = router.poll("orphan")
    assert final.status == "ok"
    assert final.tokens == _serial_tokens(params, q.prompt, 4)


def test_add_replica_rejects_duplicate_id(devices, params):
    router = Router([_replica(params, "r0")])
    with pytest.raises(ValueError, match="already in the fleet"):
        router.add_replica(_replica(params, "r0"))


# -- 5. the elastic loop end to end -----------------------------------------


def test_router_autoscales_up_then_down_with_fake_clock(devices,
                                                        params):
    """The full control loop on a deterministic clock: a burst trips
    the up signal (replica_factory builds 'auto0'), the drained queue
    trips the down signal (the least-loaded replica drains WITH
    migration), every request finishes ok, and the fleet lands back at
    min_replicas."""
    t = [0.0]

    def clock():
        t[0] += 0.25
        return t[0]

    built = []

    def factory(rid):
        rep = _replica(params, rid, device=devices[1])
        built.append(rid)
        return rep

    auto = Autoscaler(AutoscaleConfig(
        min_replicas=1, max_replicas=2, queue_high=2.0, queue_low=1.0,
        dwell_s=0.4, cooldown_s=1.0))
    router = Router([_replica(params, "r0", device=devices[0])],
                    clock=clock, autoscaler=auto,
                    replica_factory=factory)
    rng = np.random.default_rng(13)
    reqs = [Request(id=f"e{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + i % 4)),
                    max_new_tokens=6)
            for i in range(8)]
    for q in reqs:
        assert router.submit(q)
    router.drain()
    assert built == ["auto1"]        # ordinal continues the fleet's
    # the drained fleet is idle; keep the control loop ticking so the
    # down signal earns its dwell + cooldown and fires
    for _ in range(16):
        router.step()
    actions = [d["action"] for d in auto.decisions]
    assert actions[0] == "up" and "down" in actions
    for q in reqs:
        got = router.poll(q.id)
        assert got is not None and got.status == "ok", (q.id, got)
        assert got.tokens == _serial_tokens(params, q.prompt, 6), q.id
    s = router.summary()
    assert s["cluster_replicas_live"] == 1     # back at the floor
    assert s["cluster_shed"] == 0
    # no duplicated results: one Result per request id
    ids = [r.id for r in router.results()]
    assert sorted(ids) == sorted(q.id for q in reqs)
    router.close()


def test_cli_serve_cluster_elastic_smoke(devices, capsys, tmp_path):
    """The serve-cluster verb with the elastic flags: autoscaler armed
    and a shared compile cache — epilogue reports both, the summary
    parses, and a SECOND run against the same cache opens warm."""
    from idc_models_tpu.cli import main

    cc_dir = str(tmp_path / "cc")
    argv = [
        "serve-cluster", "--replicas", "1", "--autoscale-max", "2",
        "--vocab", "11", "--t-max", "32", "--embed-dim", "32",
        "--num-heads", "2", "--mlp-dim", "64", "--num-blocks", "2",
        "--slots", "2", "--window", "4", "--requests", "6",
        "--compile-cache", cc_dir]
    assert main(argv) == 0
    out = capsys.readouterr().out
    assert "autoscaler:" in out and "bounds [1, 2]" in out
    assert "-> 1 store(s)" in out
    summary = json.loads(out.split("cluster summary: ", 1)[1]
                         .splitlines()[0])
    assert summary["cluster_requests"] == 6
    assert summary["cluster_shed"] == 0
    assert main(argv) == 0                 # same cache: warm open
    out2 = capsys.readouterr().out
    assert "1 hit(s)" in out2 and "0 miss(es)" in out2


def test_sigterm_handler_unwinds_to_drain():
    """The serve verbs' SIGTERM contract at the mechanism level: armed
    handler raises _DrainRequested in the main thread; disarm restores
    the previous disposition."""
    import os
    import signal

    from idc_models_tpu.cli import (
        _DrainRequested, _arm_sigterm, _disarm_sigterm,
    )

    prev = _arm_sigterm()
    try:
        with pytest.raises(_DrainRequested):
            os.kill(os.getpid(), signal.SIGTERM)
    finally:
        _disarm_sigterm(prev)
    assert signal.getsignal(signal.SIGTERM) == (
        prev if prev is not None else signal.SIG_DFL)


def test_docs_cover_elasticity():
    """Satellite doc gate: the ROBUSTNESS "Elasticity" section, the
    BENCHMARKS elastic keys, and the README flags must all exist so
    the elastic layer stays discoverable."""
    from pathlib import Path

    root = Path(__file__).parent.parent
    robust = (root / "docs" / "ROBUSTNESS.md").read_text()
    assert "Elasticity" in robust
    for needle in ("dwell", "cooldown", "compile_cache",
                   "slot migration", "SIGTERM"):
        assert needle in robust, f"docs/ROBUSTNESS.md missing {needle}"
    bench_md = (root / "docs" / "BENCHMARKS.md").read_text()
    for needle in ("`elastic_tokens_per_sec`",
                   "`elastic_spinup_speedup`",
                   "`elastic_scale_ups`",
                   "`elastic_slot_migrations`"):
        assert needle in bench_md, f"docs/BENCHMARKS.md missing {needle}"
    readme = (root / "README.md").read_text()
    for needle in ("--autoscale-max", "--compile-cache", "SIGTERM"):
        assert needle in readme, f"README.md missing {needle}"
