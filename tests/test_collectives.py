"""Direct unit tests for the communication backend (SURVEY.md D5):
every exposed collective, exercised under shard_map on the 8-device
virtual mesh — including a hand-built ppermute ring reduction, the
primitive a ring schedule would use."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from idc_models_tpu import collectives, mesh as meshlib
from idc_models_tpu.compat import shard_map

N = 8


def _run(body, vals, out_specs=P(), n=N):
    mesh = meshlib.data_mesh(n)
    f = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=P(meshlib.DATA_AXIS),
                              out_specs=out_specs, check_vma=False))
    return f(vals)


def test_psum_pmean_match_numpy():
    vals = np.arange(N * 3, dtype=np.float32).reshape(N, 3)

    def body(x):
        return (collectives.psum(x[0], meshlib.DATA_AXIS),
                collectives.pmean(x[0], meshlib.DATA_AXIS))

    s, m = _run(body, vals, out_specs=(P(), P()))
    np.testing.assert_allclose(np.asarray(s), vals.sum(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m), vals.mean(0), rtol=1e-6)


def test_weighted_pmean_matches_numpy():
    vals = np.random.default_rng(0).normal(size=(N, 4)).astype(np.float32)
    w = np.asarray([3, 0, 1, 2, 0, 5, 1, 1], np.float32)

    def body(x, wi):
        return collectives.weighted_pmean(x[0], wi[0], meshlib.DATA_AXIS)

    mesh = meshlib.data_mesh(N)
    f = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(meshlib.DATA_AXIS), P(meshlib.DATA_AXIS)),
        out_specs=P(), check_vma=False))
    got = np.asarray(f(vals, w))
    want = (vals * w[:, None]).sum(0) / w.sum()
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # zero-weight members are excluded entirely (client-dropout
    # tolerance): even NaN values from a dead member cannot poison it
    got_drop = np.asarray(f(np.where(w[:, None] > 0, vals, np.nan), w))
    np.testing.assert_allclose(got_drop, want, rtol=1e-5)
    # negative weights are clamped to 0 (treated as dropped)
    w_neg = w.copy()
    w_neg[1] = -7.0
    np.testing.assert_allclose(np.asarray(f(vals, w_neg)), want, rtol=1e-5)
    # every member dropped: zeros, never NaN
    np.testing.assert_array_equal(
        np.asarray(f(vals, np.zeros_like(w))), 0.0)


def test_all_gather_and_axis_helpers():
    vals = np.arange(N, dtype=np.float32).reshape(N, 1)

    def body(x):
        g = collectives.all_gather(x[0], meshlib.DATA_AXIS)
        return (g, collectives.axis_index(meshlib.DATA_AXIS)[None],
                jnp.asarray(collectives.axis_size(meshlib.DATA_AXIS))[None])

    g, idx, size = _run(
        body, vals, out_specs=(P(), P(meshlib.DATA_AXIS), P()))
    np.testing.assert_array_equal(np.asarray(g).reshape(-1),
                                  np.arange(N, dtype=np.float32))
    np.testing.assert_array_equal(np.asarray(idx), np.arange(N))
    assert int(np.asarray(size)[0]) == N


def test_largest_dividing_mesh():
    assert meshlib.largest_dividing_mesh(8, 8) == 8
    assert meshlib.largest_dividing_mesh(10, 8) == 5
    assert meshlib.largest_dividing_mesh(8, 1) == 1
    assert meshlib.largest_dividing_mesh(7, 4) == 1
    assert meshlib.largest_dividing_mesh(3, 16) == 3


def test_ppermute_ring_reduce_equals_psum():
    """N-1 ring shifts with accumulation == psum: the manual ring
    schedule built from the exposed primitives works."""
    vals = np.random.default_rng(1).normal(size=(N, 5)).astype(np.float32)
    perm = collectives.ring_perm(N)
    assert perm[0] == (0, 1) and perm[-1] == (N - 1, 0)

    def body(x):
        acc = x[0]
        buf = x[0]
        for _ in range(N - 1):
            buf = collectives.ppermute(buf, meshlib.DATA_AXIS, perm)
            acc = acc + buf
        return acc - collectives.psum(x[0], meshlib.DATA_AXIS)

    diff = _run(body, vals)
    np.testing.assert_allclose(np.asarray(diff), 0.0, atol=1e-5)


def test_ring_psum_equals_psum():
    """The explicit chunked ring all-reduce (reduce-scatter + all-gather
    over ppermute hops) matches psum: fp within summation-order
    tolerance, int32 bit-exact (mask cancellation relies on that), and
    sizes that don't divide by N exercise the padding path."""
    rng = np.random.default_rng(3)
    for size in (N * 4, 13, 1):
        vals = rng.normal(size=(N, size)).astype(np.float32)

        def body(x):
            return (collectives.ring_psum(x[0], meshlib.DATA_AXIS),
                    collectives.psum(x[0], meshlib.DATA_AXIS))

        ring, ref = _run(body, vals, out_specs=(P(), P()))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    ivals = rng.integers(-2**30, 2**30, size=(N, 7), dtype=np.int32)

    def ibody(x):
        return (collectives.ring_psum(x[0], meshlib.DATA_AXIS),
                collectives.psum(x[0], meshlib.DATA_AXIS))

    iring, iref = _run(ibody, ivals, out_specs=(P(), P()))
    np.testing.assert_array_equal(np.asarray(iring), np.asarray(iref))

    # a 2-D shape round-trips through the flatten/unflatten
    vals2 = rng.normal(size=(N, 3, 5)).astype(np.float32)

    def body2(x):
        return collectives.ring_psum(x[0], meshlib.DATA_AXIS)

    out2 = _run(body2, vals2)
    np.testing.assert_allclose(np.asarray(out2), vals2.sum(0), rtol=1e-5,
                               atol=1e-5)

    # odd ring sizes (different wrap/ownership patterns than n=8), incl.
    # a size-1 "ring" (the identity early-return)
    for n in (3, 5, 1):
        valsn = rng.normal(size=(n, 11)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(_run(body2, valsn, n=n)),
                                   valsn.sum(0), rtol=1e-5, atol=1e-5)


def test_reduce_scatter_shards_the_sum():
    vals = np.random.default_rng(2).normal(size=(N, N * 2)).astype(np.float32)

    def body(x):
        return collectives.reduce_scatter(x[0], meshlib.DATA_AXIS)[None]

    out = _run(body, vals, out_specs=P(meshlib.DATA_AXIS))
    np.testing.assert_allclose(np.asarray(out).reshape(-1), vals.sum(0),
                               rtol=1e-5)
