"""CLI arg parsing + preset table (C19 parity). Heavy preset runs are
exercised by the driver / manual smoke; here we pin the flag surface."""

import pytest

from idc_models_tpu.cli import _parse
from idc_models_tpu.configs import PRESETS, get_preset


def test_presets_match_reference_constants():
    vgg = get_preset("vgg")
    assert (vgg.lr, vgg.batch_size, vgg.fine_tune_at) == (1e-3, 32, 15)
    mob = get_preset("mobile")
    assert (mob.lr, mob.fine_tune_at) == (1e-4, 100)
    dense = get_preset("dense")
    assert (dense.num_outputs, dense.per_replica_batch,
            dense.fine_tune_at, dense.repeats) == (10, True, 150, 2)
    assert get_preset("vgg").repeats == 1
    fed = get_preset("fed")
    assert (fed.num_clients, fed.fine_tune_at) == (10, 15)
    sec = get_preset("secure-fed")
    assert (sec.image_size, sec.local_epochs) == (10, 5)
    assert set(PRESETS) == {"vgg", "mobile", "dense", "fed", "secure_fed"}


def test_parse_dist_flags():
    ns = _parse(["vgg", "--path", "/tmp/x", "--epochs", "3",
                 "--fine-tune-at", "11", "--host-devices", "8"])
    assert ns.preset_key == "vgg" and ns.epochs == 3
    assert ns.fine_tune_at == 11 and ns.host_devices == 8


def test_parse_fed_flags():
    ns = _parse(["fed", "--rounds", "5", "--noniid", "--num-clients", "4"])
    assert ns.rounds == 5 and ns.iid is False and ns.num_clients == 4
    ns2 = _parse(["fed"])
    assert ns2.iid is None  # preset default (IID) applies


def test_parse_secure_flags():
    ns = _parse(["secure-fed", "--percent", "0.25", "--paillier"])
    assert ns.preset_key == "secure_fed"
    assert ns.percent == 0.25 and ns.paillier is True


def test_unknown_preset_raises():
    with pytest.raises(KeyError):
        get_preset("nope")
