"""The learned draft model (models/draft_lm.py) across its whole arc:
distillation through `train/loop.fit`, the sharded-checkpoint
round-trip (including cross-mesh restore), the serve-side contracts —
bit-identical greedy output spec-on vs spec-off, zero jit-cache growth
across mixed draft-hit patterns, slot migration carrying drafter
state — the ChainedDrafter composition rules, and the teaching errors
at every misuse point (malformed `propose()` returns at the
scheduler's one validation choke point, engine construction misfits).

The drafter is deliberately left UNTRAINED in the serve tests: the
verify program makes any drafter sound, so parity/recompile gates must
hold regardless of draft quality (bench.py's non-repetitive bench owns
the accept-rate-with-a-TRAINED-drafter story).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import draft_lm as dlm
from idc_models_tpu.models.draft import ChainedDrafter, NGramDrafter
from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import LMServer, Request, SlotEngine

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2
K = 3


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


@pytest.fixture(scope="module")
def drafter():
    cfg = dlm.draft_config(VOCAB, SEQ)
    dparams = dlm.draft_lm(cfg).init(jax.random.key(1)).params
    return dlm.DraftLM(K, dparams, cfg)


def _kw(mesh=None):
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)


def _serial_tokens(gen, prompt, steps):
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps)
    return toks.tolist()[0]


# -- distillation + checkpoint ------------------------------------------


def test_distill_through_fit_and_checkpoint_roundtrip(tmp_path):
    """The recipe end to end: the target's own greedy streams as the
    corpus, KL distillation through the STANDARD train/loop.fit, and
    the save/load round-trip (sharded tree + config sidecar) restoring
    the params bit-identically."""
    model = attention_lm(VOCAB, SEQ, embed_dim=16, num_heads=2,
                         mlp_dim=32, num_blocks=1)
    variables = model.init(jax.random.key(2))
    rng = np.random.default_rng(3)
    prompts = rng.integers(0, VOCAB, (8, 3))
    streams = dlm.greedy_streams(model, variables, prompts, SEQ)
    assert streams.shape == (8, SEQ)
    assert (streams[:, :3] == prompts).all()

    cfg = dlm.draft_config(VOCAB, SEQ, embed_dim=16, mlp_dim=32,
                           num_blocks=1)
    _, state, history = dlm.distill_draft_lm(
        model, variables, streams, config=cfg,
        mesh=meshlib.data_seq_mesh(1, 2), epochs=3, batch_size=8,
        lr=1e-2, seed=4)
    # KL against the teacher demonstrably decreases over epochs
    assert history["loss"][-1] < history["loss"][0]

    host = jax.device_get(state.params)
    dlm.save_draft_lm(tmp_path / "d", host, config=cfg).wait()
    restored, rcfg = dlm.load_draft_lm(tmp_path / "d")
    assert rcfg == cfg
    flat_a = jax.tree.leaves(host)
    flat_b = jax.tree.leaves(restored)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored drafter proposes exactly what the saved one does
    h = streams[0, :10]
    np.testing.assert_array_equal(
        dlm.DraftLM(K, host, cfg).propose(h),
        dlm.DraftLM(K, restored, rcfg).propose(h))


def test_ckpt_cross_mesh_restore_bit_identical_proposals(
        devices, tmp_path, drafter):
    """A drafter saved from host params restores onto DIFFERENT mesh
    shapes (FSDP vs TP rule resolution, registry "draft_lm" rules) with
    bit-identical params — so its proposals are bit-identical too."""
    host = jax.device_get(drafter.params)
    dlm.save_draft_lm(tmp_path / "d", host, config=drafter.config).wait()
    hist = np.arange(1, 9) % VOCAB
    want = drafter.propose(hist)
    for mesh in (meshlib.fsdp_tp_mesh(fsdp=2),
                 meshlib.fsdp_tp_mesh(tp=2)):
        restored, rcfg = dlm.load_draft_lm(tmp_path / "d", mesh=mesh)
        got = jax.device_get(restored)
        for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            dlm.DraftLM(K, got, rcfg).propose(hist), want)
    # a bare sharded tree without the sidecar is refused with the
    # teaching error, not a KeyError
    from idc_models_tpu.checkpoint import save_sharded

    save_sharded(str(tmp_path / "bare"), host).wait()
    with pytest.raises(FileNotFoundError, match="draft_config.json"):
        dlm.load_draft_lm(tmp_path / "bare")


# -- ChainedDrafter -----------------------------------------------------


class _Fixed:
    """Host drafter stub: returns a fixed row, or None."""

    def __init__(self, k, row):
        self.k = k
        self.row = row
        self.calls = 0

    def propose(self, history):
        self.calls += 1
        return self.row


def test_chained_drafter_first_hit_wins_and_validation(drafter):
    a = _Fixed(K, None)
    b = _Fixed(K, np.arange(K, dtype=np.int32))
    c = _Fixed(K, np.full(K, 7, np.int32))
    chain = ChainedDrafter(a, b, c)
    got = chain.propose(np.arange(5))
    np.testing.assert_array_equal(got, b.row)       # first non-None
    assert (a.calls, b.calls) == (1, 1)
    assert c.calls == 0                             # never consulted
    assert ChainedDrafter(a, c).propose(np.arange(5))[0] == 7
    # composition rules are teaching errors at construction
    with pytest.raises(ValueError, match="at least 2"):
        ChainedDrafter(a)
    with pytest.raises(ValueError, match="disagree on k"):
        ChainedDrafter(_Fixed(2, None), _Fixed(3, None))
    with pytest.raises(ValueError, match="ONE set of drafter ring"):
        ChainedDrafter(drafter, drafter)
    # the learned handle surfaces the (single) engine-backed member
    assert ChainedDrafter(a, drafter).learned is drafter
    assert ChainedDrafter(a, b).learned is None


# -- serve integration: parity, zero-recompile, migration ---------------


def test_learned_spec_parity_and_zero_recompile(devices, params,
                                                drafter):
    """The tentpole gates on CPU: spec-on with the learned drafter
    emits bit-identical greedy tokens to spec-off, and mixed
    draft-hit patterns (plain windows, full verifies, partial accepts)
    grow no jit cache after the first admission wave."""
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=K, drafter=drafter, **_kw())
    rng = np.random.default_rng(5)
    reqs = [Request(id=f"r{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + 2 * i)),
                    max_new_tokens=4 + (i % 4) * 3)
            for i in range(6)]
    server.run([(0.0, r) for r in reqs[:2]])
    sizes = server.engine.cache_sizes()
    # the drafter's own programs are in the frozen counter set
    assert {"propose", "draft_ingest", "draft_insert"} <= set(sizes)
    server.run([(0.0, r) for r in reqs[2:]])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    summary = server.summary()
    assert summary["serve_spec_drafted"] > 0
    assert summary["serve_spec_propose_s"] is not None

    gen = Generator(params, **_kw())
    for r in reqs:
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert got.tokens == want, (r.id, got.tokens, want)


def test_chained_drafter_serves_with_batched_learned_member(
        devices, params, drafter):
    """The production composition through the scheduler's batched
    path: lookup-first/learned-fallback emits the same tokens as
    plain decode (any drafter is sound), and the learned member's
    device backlog is drained even on lookup-hit cycles."""
    chain = ChainedDrafter(NGramDrafter(K, order=3), drafter)
    server = LMServer(params, n_slots=2, window=4, spec_decode=True,
                      draft_k=K, drafter=chain, **_kw())
    rng = np.random.default_rng(6)
    reqs = [Request(id=f"c{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + 3 * i)),
                    max_new_tokens=6 + 2 * i)
            for i in range(4)]
    server.run([(0.0, r) for r in reqs])
    gen = Generator(params, **_kw())
    for r in reqs:
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        assert got.tokens == _serial_tokens(gen, r.prompt,
                                            r.max_new_tokens)


def test_migration_carries_drafter_state(devices, params, drafter):
    """PR 18's live slot migration extended to drafter state: a
    mid-decode slot exported from one spec-armed engine and imported
    into another resumes with bit-identical output — including the
    drafter's ring rows and pending-token backlog."""
    src = SlotEngine(params, n_slots=2, draft_k=K, draft_model=drafter,
                     **_kw())
    src.warmup(4)
    dst = SlotEngine(params, n_slots=2, draft_k=K, draft_model=drafter,
                     **_kw())
    dst.warmup(4)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, VOCAB, 7)
    src.admit(1, prompt, 12)
    mid = src.step_window(4)[1]              # decode a bit, then move
    snap = src.export_slot(1)
    assert snap["draft"]["front"] > 0
    dst.import_slot(0, snap)
    rest = []
    for _ in range(20):
        if not dst._occupied[0]:
            break
        r = dst.propose_all()
        if r is None:
            rest.extend(dst.step_window(4).get(0, []))
        else:
            drafts, live = r
            dst.begin_verify(drafts, live)
            rest.extend(dst.collect()[0])
        if dst._occupied[0] and dst._rem_h[0] < 1:
            dst.release(0)
    gen = Generator(params, **_kw())
    want = _serial_tokens(gen, prompt, 12)
    assert mid + rest == want, (mid, rest, want)

    # presence mismatches are teaching errors BOTH ways
    plain = SlotEngine(params, n_slots=1, **_kw())
    plain.warmup(4)
    plain.admit(0, prompt, 9)
    plain.step_window(4)
    with pytest.raises(ValueError, match="no learned-drafter state"):
        dst.import_slot(1, plain.export_slot(0))
    plain.release(0)                      # export does not free the slot
    src.admit(0, prompt, 9)
    src.step_window(4)
    with pytest.raises(ValueError, match="no draft_model"):
        plain.import_slot(0, src.export_slot(0))


# -- teaching errors ----------------------------------------------------


class _Settable:
    """Drafter whose next proposal the test scripts."""

    def __init__(self, k):
        self.k = k
        self.row = None

    def propose(self, history):
        return self.row


def test_malformed_propose_teaching_errors(devices, params):
    """Every malformed `propose()` return dies at the scheduler's ONE
    validation choke point with a message naming the drafter class and
    the contract — never a raw jit shape error downstream."""
    bad = _Settable(K)
    server = LMServer(params, n_slots=1, window=4, spec_decode=True,
                      draft_k=K, drafter=bad, **_kw())
    cases = [
        (np.zeros(K, np.float32), "dtype float32"),
        (np.zeros((1, K), np.int32), "ONE flat row"),
        (np.zeros(K + 1, np.int32), f"compiled at exactly k={K}"),
        (np.full(K, VOCAB, np.int32), "out-of-vocab id"),
    ]
    # each raise ABORTS the running request (the scheduler cannot
    # trust device state after a mid-cycle failure), so every case
    # gets a fresh one
    for i, (row, msg) in enumerate(cases):
        bad.row = None
        server.submit(Request(id=f"m{i}", prompt=(1, 2, 3),
                              max_new_tokens=12))
        server.step()                              # admission cycle
        bad.row = row
        with pytest.raises(ValueError) as e:
            for _ in range(4):
                server.step()
        assert "_Settable.propose returned" in str(e.value)
        assert msg in str(e.value)
        assert "models/draft.py contract" in str(e.value)
    # a well-formed row (and None) flow on untouched
    bad.row = None
    server.submit(Request(id="ok", prompt=(1, 2, 3),
                          max_new_tokens=12))
    server.step()
    bad.row = np.zeros(K, np.int32)
    server.step()
    bad.row = None
    server.step()


def test_engine_drafter_construction_teaching_errors(params, drafter):
    """Misfits between drafter and engine die at construction with
    errors that say what to change."""
    with pytest.raises(ValueError, match="needs draft_k"):
        SlotEngine(params, n_slots=1, draft_model=drafter, **_kw())
    cfg13 = dlm.draft_config(13, SEQ)
    d13 = dlm.DraftLM(K, dlm.draft_lm(cfg13).init(
        jax.random.key(8)).params, cfg13)
    with pytest.raises(ValueError, match="share one tokenizer"):
        SlotEngine(params, n_slots=1, draft_k=K, draft_model=d13,
                   **_kw())
    short = dlm.draft_config(VOCAB, SEQ // 2)
    dshort = dlm.DraftLM(K, dlm.draft_lm(short).init(
        jax.random.key(9)).params, short)
    with pytest.raises(ValueError, match="seq_len >= t_max"):
        SlotEngine(params, n_slots=1, draft_k=K, draft_model=dshort,
                   **_kw())
    with pytest.raises(ValueError, match="without a learned drafter"):
        LMServer(params, n_slots=1, spec_decode=True, draft_k=K,
                 draft_partition_rules=(), **_kw())
