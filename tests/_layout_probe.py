"""Backend probe for the client-layout-invariance tests (ISSUE 4
satellite): does THIS jax/XLA build compute the same per-client local
training result regardless of how clients are laid out over devices?

`test_client_count_independent_of_device_count` (test_federated.py) and
`test_secure_round_layout_invariant` (test_secure.py) assert that k
clients per device is a pure layout choice — the same 8 clients on an
8-device mesh (k=1) and a 4-device mesh (k=2) must produce the same
round to rtol=1e-5. On this container (jax 0.4.37, XLA:CPU) that
contract is broken BELOW the framework: a scan-wrapped
value-and-grad training step under ``vmap`` under ``shard_map``
produces genuinely different numbers at different vmap widths, down to
the FIRST batch loss (≈1e-2 shifts — a different dropout realization,
not float reassociation), while every ingredient in isolation is
layout-stable:

- per-client fold_in/split/permutation/bernoulli chains: bit-identical
  across layouts (integer threefry, verified directly);
- the same step WITHOUT lax.scan: identical across layouts to 1 ulp;
- plain jit(vmap(local_train)) at widths 1/2/8: identical to 1 ulp;
- `jax_threefry_partitionable=True` does not change the outcome.

The divergence needs the full composite — lax.scan + AD + dropout
inside vmap inside shard_map — i.e. it is an XLA:CPU/jax-0.4.37
lowering artifact of exactly the program `make_local_trainer` builds,
unfixable from framework code (rmsprop's Keras-form update
g/(sqrt(nu)+eps) then amplifies the wrong dropout realization into the
observed ~1e-3 parameter mismatches). The two tests have failed
identically since the seed tree for this reason.

`layout_invariant()` runs a minimal discriminating reproducer once per
session; the tests skip with this module's story when it returns False,
and run for real on backends where the contract holds (TPU, newer
XLA:CPU).
"""

from __future__ import annotations

import functools

import numpy as np


@functools.lru_cache(maxsize=1)
def layout_invariant() -> bool:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from idc_models_tpu import collectives
    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.compat import shard_map
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train.losses import binary_cross_entropy

    model = small_cnn(10, 3, 1)
    imgs, labels = synthetic.make_idc_like(8 * 32, size=10, seed=7)
    imgs = np.asarray(imgs, np.float32).reshape(8, 32, 10, 10, 3)
    labels = np.asarray(labels, np.float32).reshape(8, 32)
    v = model.init(jax.random.key(0))
    rng = jax.random.key(3)

    def local_train(params, state, im, lb, kk):
        # the discriminating composite is make_local_trainer's EXACT
        # shape — epoch scan around a step scan around a permutation-
        # indexed, dropout-consuming value_and_grad step. Simplified
        # variants (no permutation/epoch nesting) only differ at the
        # ulp level across layouts; this full shape reproduces the
        # ~1e-2 different-random-realization pathology the gate exists
        # for (measured: client-1 first-batch loss 0.6979 vs 0.6857
        # between the k=1 and k=2 layouts on jax 0.4.37 XLA:CPU).
        def local_step(carry, inp):
            params_, idx, step_rng = carry[0], inp[0], inp[1]
            x, y = im[idx], lb[idx]

            def loss_of(p):
                logits, _ = model.apply(p, state, x, train=True,
                                        rng=step_rng)
                return binary_cross_entropy(
                    logits.astype(jnp.float32), y)

            loss, g = jax.value_and_grad(loss_of)(params_)
            params_ = jax.tree.map(lambda a, b: a - 1e-3 * b, params_, g)
            return (params_,), loss

        def epoch(carry, epoch_rng):
            perm_rng, steps_rng = jax.random.split(epoch_rng)
            perm = jax.random.permutation(perm_rng, 32)
            idx = perm.reshape(1, 32)
            step_rngs = jax.random.split(steps_rng, 1)
            return lax.scan(local_step, carry, (idx, step_rngs))

        _, losses = lax.scan(epoch, (params,), jax.random.split(kk, 1))
        return losses

    def losses_for(n_dev):
        mesh = meshlib.client_mesh(n_dev)
        k = 8 // n_dev

        def per_device(params, state, im, lb, r):
            dev = collectives.axis_index(meshlib.CLIENT_AXIS)
            cids = dev * k + jnp.arange(k)
            ks = jax.vmap(lambda c: jax.random.fold_in(r, c))(cids)
            return jax.vmap(local_train,
                            in_axes=(None, None, 0, 0, 0))(
                params, state, im, lb, ks)

        f = shard_map(per_device, mesh=mesh,
                      in_specs=(P(), P(), P(meshlib.CLIENT_AXIS),
                                P(meshlib.CLIENT_AXIS), P()),
                      out_specs=P(meshlib.CLIENT_AXIS), check_vma=False)
        return np.asarray(jax.jit(f)(v.params, v.state, imgs, labels,
                                     rng))

    # compared at the TESTS' tolerance, not bitwise: a backend whose
    # lowering differs only by benign float reassociation (well inside
    # rtol=1e-5) must still RUN the layout-invariance tests — only the
    # ~1e-2 different-random-realization pathology should gate them
    return bool(np.allclose(losses_for(8), losses_for(4),
                            rtol=1e-5, atol=1e-6))


LAYOUT_SKIP_REASON = (
    "backend lowers the vmapped+scanned local-training program "
    "layout-dependently (different dropout realizations per vmap width "
    "under shard_map — jax/XLA:CPU artifact, probed by "
    "tests/_layout_probe.py; failed identically since the seed tree, "
    "root-caused in PR 4): the k-clients-per-device layout-invariance "
    "contract is unverifiable at rtol=1e-5 here")
