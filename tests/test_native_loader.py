"""Native (C++/libpng) loader: build, decode correctness vs PIL, resize,
robustness, and integration with load_directory."""

import numpy as np
import pytest
from PIL import Image

from idc_models_tpu.data import native
from idc_models_tpu.data.idc import load_directory

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native loader unavailable: {native.build_error()}")


def _write_pngs(root, n_per_class=4, size=50, seed=0, mode="RGB"):
    rng = np.random.default_rng(seed)
    for label in ("0", "1"):
        d = root / label
        d.mkdir(parents=True, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 256, (size, size, 3), np.uint8)
            img = Image.fromarray(arr, "RGB").convert(mode)
            img.save(d / f"p{i}.png")


def test_decode_matches_pil_no_resize(tmp_path):
    _write_pngs(tmp_path, size=50)
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 50)
    assert got.shape == (len(files), 50, 50, 3) and got.dtype == np.float32
    for i, f in enumerate(files):
        ref = np.asarray(Image.open(f).convert("RGB"), np.float32) / 255.0
        np.testing.assert_array_equal(got[i], ref)


def test_decode_grayscale_and_palette(tmp_path):
    _write_pngs(tmp_path, n_per_class=2, size=20, mode="L")
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 20)
    for i, f in enumerate(files):
        ref = np.asarray(Image.open(f).convert("RGB"), np.float32) / 255.0
        np.testing.assert_allclose(got[i], ref, atol=1 / 255.0)


def test_resize_matches_python_backend(tmp_path):
    """Native resize implements the same naive-bilinear/half-pixel math as
    the Python fallback (both mirroring tf.image.resize defaults,
    dist_model_tf_vgg.py:42) — backends must be interchangeable."""
    from idc_models_tpu.data.idc import _decode_one

    _write_pngs(tmp_path, n_per_class=2, size=50)
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 10)
    assert got.shape[1:] == (10, 10, 3)
    for i, f in enumerate(files):
        ref = _decode_one(f, 10)
        np.testing.assert_allclose(got[i], ref, atol=1e-5)


def test_bad_file_zeroed_not_fatal(tmp_path):
    _write_pngs(tmp_path, n_per_class=1, size=10)
    bad = tmp_path / "0" / "bad.png"
    bad.write_bytes(b"not a png")
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 10)
    i_bad = files.index(str(bad))
    np.testing.assert_array_equal(got[i_bad], 0.0)
    assert got[(i_bad + 1) % len(files)].max() > 0


def test_all_bad_raises(tmp_path):
    bad = tmp_path / "b.png"
    bad.write_bytes(b"nope")
    with pytest.raises(ValueError):
        native.decode_batch([str(bad)], 10)


def test_load_directory_native_equals_pil(tmp_path):
    _write_pngs(tmp_path, n_per_class=3, size=12)
    ds_nat = load_directory(tmp_path, image_size=12, seed=7,
                            backend="native")
    ds_pil = load_directory(tmp_path, image_size=12, seed=7, backend="pil")
    np.testing.assert_array_equal(ds_nat.labels, ds_pil.labels)
    np.testing.assert_array_equal(ds_nat.images, ds_pil.images)
