"""Native (C++/libpng) loader: build, decode correctness vs PIL, resize,
robustness, and integration with load_directory."""

import numpy as np
import pytest
from PIL import Image

from idc_models_tpu.data import native
from idc_models_tpu.data.idc import load_directory

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native loader unavailable: {native.build_error()}")


def _write_pngs(root, n_per_class=4, size=50, seed=0, mode="RGB"):
    rng = np.random.default_rng(seed)
    for label in ("0", "1"):
        d = root / label
        d.mkdir(parents=True, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 256, (size, size, 3), np.uint8)
            img = Image.fromarray(arr, "RGB").convert(mode)
            img.save(d / f"p{i}.png")


def test_decode_matches_pil_no_resize(tmp_path):
    _write_pngs(tmp_path, size=50)
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 50)
    assert got.shape == (len(files), 50, 50, 3) and got.dtype == np.float32
    for i, f in enumerate(files):
        ref = np.asarray(Image.open(f).convert("RGB"), np.float32) / 255.0
        np.testing.assert_array_equal(got[i], ref)


def test_decode_grayscale_and_palette(tmp_path):
    _write_pngs(tmp_path, n_per_class=2, size=20, mode="L")
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 20)
    for i, f in enumerate(files):
        ref = np.asarray(Image.open(f).convert("RGB"), np.float32) / 255.0
        np.testing.assert_allclose(got[i], ref, atol=1 / 255.0)


def test_resize_matches_python_backend(tmp_path):
    """Native resize implements the same naive-bilinear/half-pixel math as
    the Python fallback (both mirroring tf.image.resize defaults,
    dist_model_tf_vgg.py:42) — backends must be interchangeable."""
    from idc_models_tpu.data.idc import _decode_one

    _write_pngs(tmp_path, n_per_class=2, size=50)
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    got = native.decode_batch(files, 10)
    assert got.shape[1:] == (10, 10, 3)
    for i, f in enumerate(files):
        ref = _decode_one(f, 10)
        np.testing.assert_allclose(got[i], ref, atol=1e-5)


def test_bad_file_raises_naming_the_file(tmp_path):
    """Default is loud, like the PIL backend: backend='auto' must not
    silently train on zero images carrying real labels."""
    _write_pngs(tmp_path, n_per_class=1, size=10)
    bad = tmp_path / "0" / "bad.png"
    bad.write_bytes(b"not a png")
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    with pytest.raises(ValueError, match="bad.png"):
        native.decode_batch(files, 10)


def test_bad_file_zeroed_when_opted_in(tmp_path):
    _write_pngs(tmp_path, n_per_class=1, size=10)
    bad = tmp_path / "0" / "bad.png"
    bad.write_bytes(b"not a png")
    files = sorted(str(p) for p in tmp_path.glob("*/*.png"))
    with pytest.warns(UserWarning, match="failed to decode"):
        got = native.decode_batch(files, 10, on_error="zero")
    i_bad = files.index(str(bad))
    np.testing.assert_array_equal(got[i_bad], 0.0)
    assert got[(i_bad + 1) % len(files)].max() > 0


def test_all_bad_raises(tmp_path):
    bad = tmp_path / "b.png"
    bad.write_bytes(b"nope")
    with pytest.raises(ValueError):
        native.decode_batch([str(bad)], 10)
    # all-failed raises even in lenient mode
    with pytest.raises(ValueError, match="failed to decode"):
        native.decode_batch([str(bad)], 10, on_error="zero")
    with pytest.raises(ValueError, match="on_error"):
        native.decode_batch([str(bad)], 10, on_error="ignore")


def test_stale_abi_binary_triggers_rebuild(tmp_path, monkeypatch):
    """A wrong-ABI .so that escapes the mtime test must be rebuilt from
    source, not cached as a permanent failure."""
    import shutil

    import idc_models_tpu.data.native as nat

    src = tmp_path / "loader.cpp"
    so = tmp_path / "_native_loader.so"
    shutil.copy(nat._SRC, src)
    # build a fake ABI-0 binary, dated in the future so mtime says fresh
    import subprocess
    stub = tmp_path / "stub.cpp"
    stub.write_text('extern "C" int idc_loader_abi_version() { return 0; }')
    subprocess.run(["g++", "-O3", "-shared", "-fPIC", str(stub),
                    "-o", str(so)], check=True)
    import os as _os
    future = _os.stat(src).st_mtime + 10_000
    _os.utime(so, (future, future))

    monkeypatch.setattr(nat, "_SRC", src)
    monkeypatch.setattr(nat, "_SO", so)
    monkeypatch.setattr(nat, "_lib", None)
    monkeypatch.setattr(nat, "_build_error", None)
    assert nat.available(), nat.build_error()
    assert nat._lib.idc_loader_abi_version() == nat._ABI


def test_load_directory_native_equals_pil(tmp_path):
    _write_pngs(tmp_path, n_per_class=3, size=12)
    ds_nat = load_directory(tmp_path, image_size=12, seed=7,
                            backend="native")
    ds_pil = load_directory(tmp_path, image_size=12, seed=7, backend="pil")
    np.testing.assert_array_equal(ds_nat.labels, ds_pil.labels)
    np.testing.assert_array_equal(ds_nat.images, ds_pil.images)
