"""ISSUE 7 tentpole (c): the SLO engine — burn-rate math over sliding
windows, multi-window alert/resolve transitions, the jsonl + registry
surfaces, and the serve/federated integrations (alert under an
injected fault plan, silence on the clean baseline — the acceptance
gate)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.observe import (
    SLO, SLOEngine, JsonlLogger, MetricsRegistry, trace,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _engine(slos, clock, **kw):
    kw.setdefault("short_window_s", 10.0)
    kw.setdefault("long_window_s", 50.0)
    kw.setdefault("min_samples", 5)
    return SLOEngine(slos, clock=clock,
                     registry=kw.pop("registry", MetricsRegistry()),
                     **kw)


# -- declaration -----------------------------------------------------------


def test_slo_declarations_validate():
    s = SLO.latency("ttft", threshold_s=0.2, percentile=95.0)
    assert s.budget == pytest.approx(0.05)
    assert SLO.rate("err", budget=0.01).budget == 0.01
    with pytest.raises(ValueError):
        SLO.latency("x", threshold_s=0.0)
    with pytest.raises(ValueError):
        SLO.latency("x", threshold_s=0.1, percentile=100.0)
    with pytest.raises(ValueError):
        SLO.rate("x", budget=1.5)
    with pytest.raises(ValueError):
        SLO(name="x", kind="weird", budget=0.5)
    clock = FakeClock()
    with pytest.raises(ValueError):
        _engine([], clock)
    with pytest.raises(ValueError):
        _engine([SLO.rate("a", budget=0.1), SLO.rate("a", budget=0.2)],
                clock)
    with pytest.raises(ValueError):
        SLOEngine([SLO.rate("a", budget=0.1)], short_window_s=60,
                  long_window_s=30)


def test_kind_mismatch_and_unknown_names_are_loud():
    eng = _engine([SLO.latency("ttft", threshold_s=0.1)], FakeClock())
    assert eng.has("ttft") and not eng.has("nope")
    with pytest.raises(ValueError):
        eng.record("ttft", ok=True)       # latency kind wants observe
    with pytest.raises(ValueError):
        eng.observe("nope", 0.1)
    with pytest.raises(ValueError):
        eng.breached("nope")


# -- burn-rate math --------------------------------------------------------


def test_burn_rate_is_bad_fraction_over_budget():
    clock = FakeClock()
    reg = MetricsRegistry()
    eng = _engine([SLO.latency("ttft", threshold_s=0.1,
                               percentile=90.0)],   # budget 0.10
                  clock, registry=reg)
    # 20 samples, 4 bad -> bad fraction 0.2 -> burn 2.0
    for i in range(20):
        clock.t += 0.1
        eng.observe("ttft", 0.5 if i % 5 == 0 else 0.01)
    eng.evaluate()
    g = reg.gauge("slo_burn_rate", labels=("slo", "window"))
    assert g.value(slo="ttft", window="short") == pytest.approx(2.0)
    assert g.value(slo="ttft", window="long") == pytest.approx(2.0)


def test_samples_age_out_of_the_windows():
    clock = FakeClock()
    reg = MetricsRegistry()
    eng = _engine([SLO.rate("err", budget=0.5)], clock, registry=reg)
    for _ in range(10):
        clock.t += 0.1
        eng.record("err", ok=False)
    eng.evaluate()
    g = reg.gauge("slo_burn_rate", labels=("slo", "window"))
    assert g.value(slo="err", window="short") == pytest.approx(2.0)
    # jump past the short window: the short burn empties, the long
    # window still holds the history
    clock.t += 20.0
    eng.evaluate()
    assert g.value(slo="err", window="short") == 0.0
    assert g.value(slo="err", window="long") == pytest.approx(2.0)
    # past the long window too: everything pruned
    clock.t += 100.0
    eng.evaluate()
    assert g.value(slo="err", window="long") == 0.0


def test_alert_needs_both_windows_and_min_samples():
    clock = FakeClock()
    eng = _engine([SLO.rate("err", budget=0.05)], clock, min_samples=8)
    # 4 bad samples: over-budget but under min_samples -> no alert
    for _ in range(4):
        clock.t += 0.5
        eng.record("err", ok=False)
    assert eng.evaluate() == [] and not eng.breached("err")
    # enough samples now -> alert fires exactly once
    for _ in range(6):
        clock.t += 0.5
        eng.record("err", ok=False)
    fired = eng.evaluate()
    assert [a["slo"] for a in fired] == ["err"]
    assert eng.breached("err")
    assert eng.evaluate() == []          # hysteresis: no re-fire
    assert len(eng.alerts) == 1


def test_alert_resolves_and_can_refire(tmp_path):
    clock = FakeClock()
    log = tmp_path / "run.jsonl"
    reg = MetricsRegistry()
    with JsonlLogger(log) as logger:
        eng = _engine([SLO.rate("err", budget=0.05)], clock,
                      logger=logger, registry=reg)
        for _ in range(10):
            clock.t += 0.1
            eng.record("err", ok=False)
        eng.evaluate()
        assert eng.breached("err")
        # a healthy stretch dilutes both windows below the threshold
        for _ in range(400):
            clock.t += 0.1
            eng.record("err", ok=True)
        eng.evaluate()
        assert not eng.breached("err")
        # breach again -> a SECOND alert fires
        for _ in range(60):
            clock.t += 0.1
            eng.record("err", ok=False)
        eng.evaluate()
        assert eng.breached("err") and len(eng.alerts) == 2
    events = [json.loads(l)["event"] for l in open(log)]
    assert events.count("slo_alert") == 2
    assert events.count("slo_resolved") == 1
    assert reg.counter("slo_alerts_total",
                       labels=("slo",)).value(slo="err") == 2
    assert reg.gauge("slo_breached",
                     labels=("slo",)).value(slo="err") == 1


# -- serving integration ---------------------------------------------------


def _drive_serving(slo_engine, clock, *, ttft_s):
    """Replay a synthetic request stream through the REAL metrics-hook
    wiring (no engine compile needed): submit -> admit -> first token
    -> finish, one request per 0.2s, with the given TTFT."""
    from idc_models_tpu.serve.metrics import ServingMetrics

    m = ServingMetrics(registry=MetricsRegistry(), slo=slo_engine)
    for i in range(40):
        clock.t += 0.2
        rid = f"r{i}"
        m.on_submit(rid, clock.t)
        m.on_admit(rid, 0.01)
        m.on_first_token(rid, ttft_s)
        m.on_finish(rid, n_tokens=4, ttft_s=ttft_s, decode_s=0.05,
                    reason="budget", t=clock.t)
        m.on_cycle(queue_depth=0, occupancy=0.5, tokens=4)
    return m


def test_serving_slo_alerts_under_injected_latency_and_not_clean():
    """The acceptance gate, serve side: the same wiring fires under
    injected TTFT latency and stays silent on the clean baseline."""
    clock = FakeClock()
    eng = _engine([SLO.latency("ttft", threshold_s=0.2),
                   SLO.rate("error_rate", budget=0.05)], clock)
    _drive_serving(eng, clock, ttft_s=0.5)      # every TTFT breaches
    assert [a["slo"] for a in eng.alerts] == ["ttft"]
    assert eng.breached("ttft") and not eng.breached("error_rate")

    clock2 = FakeClock()
    eng2 = _engine([SLO.latency("ttft", threshold_s=0.2),
                    SLO.rate("error_rate", budget=0.05)], clock2)
    _drive_serving(eng2, clock2, ttft_s=0.05)   # clean baseline
    assert eng2.alerts == []
    assert not eng2.breached("ttft")


def test_serving_error_rate_counts_rejects_and_deadline():
    from idc_models_tpu.serve.metrics import ServingMetrics

    clock = FakeClock()
    eng = _engine([SLO.rate("error_rate", budget=0.05)], clock,
                  min_samples=5)
    m = ServingMetrics(registry=MetricsRegistry(), slo=eng)
    for i in range(10):
        clock.t += 0.5
        if i % 2:
            m.on_reject(f"r{i}", clock.t)
        else:
            m.on_finish(f"r{i}", n_tokens=0, ttft_s=None, decode_s=0.0,
                        reason="deadline", t=clock.t)
        m.on_cycle(queue_depth=1, occupancy=0.0)
    assert eng.breached("error_rate")


# -- federated integration -------------------------------------------------


def _fed_run(fail_round_fn, slo_engine, *, fault_plan=None, rounds=4,
             tracer=None):
    from idc_models_tpu.federated.driver import DriverConfig, run_rounds
    from idc_models_tpu.federated.fedavg import ServerState

    server = ServerState(round=jnp.zeros((), jnp.int32),
                         params={"w": jnp.ones((2,))}, model_state={})
    prev = trace.set_tracer(tracer)
    try:
        return run_rounds(
            fail_round_fn, server, None, None, np.ones(4, np.float32),
            config=DriverConfig(rounds=rounds, max_attempts=3),
            slo=slo_engine, fault_plan=fault_plan)
    finally:
        trace.set_tracer(prev)


def _round_fn(diverge_every):
    from idc_models_tpu.federated.fedavg import ServerState

    calls = {"n": 0}

    def round_fn(server, images, labels, weights, rng):
        calls["n"] += 1
        bad = diverge_every and calls["n"] % diverge_every == 1
        return (ServerState(round=server.round + 1,
                            params=server.params,
                            model_state=server.model_state),
                {"loss": jnp.float32(float("nan") if bad else 0.5),
                 "accuracy": jnp.float32(0.9),
                 "clients_dropped": jnp.int32(0)})

    return round_fn


def test_fed_driver_slo_alerts_under_fault_plan_and_not_clean():
    """The acceptance gate, federated side: a fault-plan run whose
    attempts keep diverging trips the round-failure-rate SLO; the
    clean baseline run stays silent."""
    from idc_models_tpu import faults as faults_lib

    plan = faults_lib.parse_fault_spec("nan:0-2", 4)
    eng = _engine([SLO.rate("round_failure_rate", budget=0.05)],
                  FakeClock(), min_samples=3)
    # every odd call diverges -> one failed attempt per round
    _fed_run(_round_fn(diverge_every=2), eng, fault_plan=plan)
    assert [a["slo"] for a in eng.alerts] == ["round_failure_rate"]

    eng2 = _engine([SLO.rate("round_failure_rate", budget=0.05)],
                   FakeClock(), min_samples=3)
    _fed_run(_round_fn(diverge_every=0), eng2)
    assert eng2.alerts == []


def test_fed_client_spans_carry_fault_outcomes():
    """Tentpole (b), federated half: every attempt's fed.round span
    gains one nested fed.client marker per participant, stamped with
    the plan's fault outcome for that (client, round)."""
    from idc_models_tpu import faults as faults_lib

    plan = faults_lib.parse_fault_spec("sign_flip:0-1:x1000,crash:2", 4)
    tr = trace.Tracer()
    _fed_run(_round_fn(diverge_every=0), None, fault_plan=plan,
             rounds=2, tracer=tr)
    recs = tr.records()
    by_id = {r["id"]: r for r in recs}
    rounds = [r for r in recs if r["name"] == "fed.round"]
    clients = [r for r in recs if r["name"] == "fed.client"]
    assert len(rounds) == 2
    assert len(clients) == 2 * 4          # 4 participants x 2 rounds
    for c in clients:
        parent = by_id[c["parent"]]
        assert parent["name"] == "fed.round"
        assert c["attrs"]["round"] == parent["attrs"]["round"]
    outcome = {c["attrs"]["client"]: c["attrs"]["fault"]
               for c in clients if c["attrs"]["round"] == 0}
    assert outcome == {0: "sign_flip", 1: "sign_flip", 2: "crash",
                       3: "ok"}
    flipped = [c for c in clients if c["attrs"]["fault"] == "sign_flip"]
    assert all(c["attrs"]["fault_scale"] == 1000.0 for c in flipped)
