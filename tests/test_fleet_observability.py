"""ISSUE 20: fleet observability — the cluster telemetry plane.

1. TRACING — an autoscaled 1→2→1 run with a mid-decode live slot
   migration renders, from the MERGED per-process jsonl logs alone, a
   single wall-ordered `stats --request RID` timeline: placement,
   prefill handoff, migration, and finish hops under ONE trace_id with
   per-hop latency attribution.
2. TELEMETRY — `ClusterTelemetry` folds every replica registry into
   one replica-labeled fleet exposition whose rollup series equal the
   sum of the per-replica scrapes at the same instant, and the fleet
   /healthz embeds every replica health document plus autoscaler and
   compile-cache state. The non-cluster /healthz shape is untouched.
3. SKEW — the router's pooled SLO engine fires on a fleet-wide breach
   that no single replica's engine can see (each below min_samples).
4. WATCHDOGS — each anomaly detector fires once on its injected fault,
   stays silent on a clean fleet, and emits the frozen-schema
   ``cluster_anomaly`` record.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.observe import JsonlLogger, MetricsExporter
from idc_models_tpu.observe.metrics_registry import MetricsRegistry
from idc_models_tpu.observe.slo import SLO, SLOEngine
from idc_models_tpu.observe.stats import (
    format_request_timeline, summarize_jsonl,
)
from idc_models_tpu.serve import (
    AutoscaleConfig, Autoscaler, ClusterTelemetry, ClusterWatchdog,
    CompileCache, PrefixRegistry, Request, Router, WatchdogConfig,
    build_replica,
)

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2
CHUNK = 8


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _model_kw():
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ)


def _replica(params, rid, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    return build_replica(params, replica_id=rid, **_model_kw(), **kw)


def _serial_tokens(params, prompt, steps):
    gen = Generator(params, mesh=None, cache_dtype=jnp.float32,
                    **_model_kw())
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps)
    return toks.tolist()[0]


def _records(paths):
    recs = []
    for p in paths:
        for line in p.read_text().splitlines():
            if line.strip():
                recs.append(json.loads(line))
    return recs


def _schemas(recs, event):
    return {frozenset(r) for r in recs if r.get("event") == event}


# -- 1. the acceptance drill: merged cross-replica timeline -----------------


def test_autoscaled_migration_renders_one_merged_timeline(devices,
                                                          params,
                                                          tmp_path):
    """1→2→1 under the real autoscaler with every process writing its
    OWN jsonl: a short burst scales the fleet up, two long requests
    (one per decode replica, prefilled on the dedicated prefill
    replica) ride into the scale-down, and the victim's running slot
    migrates live. Merging the four logs yields ONE timeline for the
    migrated rid — place, handoff, migrate, finish — under one
    trace_id, with per-hop deltas in the rendered view. The manual
    clock makes the scaling sequence deterministic: time only moves
    when the test advances it, so each decision fires exactly where
    injected."""
    logs = {name: JsonlLogger(tmp_path / f"{name}.jsonl")
            for name in ("router", "rp", "r0", "auto1")}
    registry = PrefixRegistry(CHUNK, 64 * 1024 * 1024,
                              logger=logs["router"])
    prefix_kw = dict(prefill_chunk=CHUNK, prefix_cache_mb=8.0,
                     shared_prefix=registry)
    rp = _replica(params, "rp", role="prefill", logger=logs["rp"],
                  **prefix_kw)
    r0 = _replica(params, "r0", window=2, logger=logs["r0"],
                  **prefix_kw)
    t = [0.0]
    auto = Autoscaler(AutoscaleConfig(
        min_replicas=1, max_replicas=2, queue_high=2.0, queue_low=1.5,
        dwell_s=0.5, cooldown_s=2.0), logger=logs["router"])
    router = Router(
        [r0, rp], prefix_registry=registry, clock=lambda: t[0],
        logger=logs["router"], autoscaler=auto,
        replica_factory=lambda rid: _replica(
            params, rid, window=2, logger=logs["auto1"], **prefix_kw))

    # phase 1: a burst of shorts trips the up signal; advancing the
    # clock past the dwell lets it fire
    shorts = [Request(id=f"s{i}", prompt=(1, 2, 3, 4),
                      max_new_tokens=2) for i in range(6)]
    for q in shorts:
        assert router.submit(q)
    router.step()                       # up signal registered at t=0
    t[0] = 1.0
    router.step()                       # dwell elapsed -> scale up
    grown = {r.replica_id for r in router.replicas} - {"r0", "rp"}
    assert len(grown) == 1
    auto_id = grown.pop()               # autoN: the router names it

    # phase 2: drain the shorts with TIME FROZEN — the down signal
    # accrues no dwell and the cooldown never elapses, so the fleet
    # deterministically stays at two decode replicas
    for _ in range(200):
        if all(router.poll(q.id) is not None for q in shorts):
            break
        router.step()
    assert all(router.poll(q.id).status == "ok" for q in shorts)

    # phase 3: two long prompts (>= one chunk: they handoff through
    # the prefill replica) land one per decode replica
    longs = [Request(id=f"big{i}", prompt=tuple(range(1, 17)),
                     max_new_tokens=12) for i in range(2)]
    for q in longs:
        assert router.submit(q)
    owners = {q.id: router._owner[q.id].replica_id for q in longs}
    assert set(owners.values()) == {"r0", auto_id}
    for _ in range(2):                  # both longs decode mid-stream
        router.step()

    # phase 4: release the clock — cooldown and dwell are instantly
    # ancient, the down decision fires, and the victim (r0: load tie,
    # lowest fleet index) slot-migrates its RUNNING request to auto1
    t[0] = 11.0
    router.step()
    assert router.slot_migrations, "the scale-down must migrate live"
    mig = router.slot_migrations[0]
    assert mig["from"] == "r0" and mig["to"] == auto_id
    rid = mig["rid"]
    for _ in range(200):
        if all(router.poll(q.id) is not None for q in longs):
            break
        router.step()
    res = {q.id: router.poll(q.id) for q in longs}
    assert all(r.status == "ok" for r in res.values())
    # the migrated stream stayed bit-identical to a serial run
    prompt = next(q.prompt for q in longs if q.id == rid)
    assert res[rid].tokens == _serial_tokens(params, prompt, 12)

    # the fleet health document embeds the autoscaler's clocks
    doc = ClusterTelemetry(router).health()
    assert set(doc["autoscaler"]) >= {
        "min_replicas", "max_replicas", "dwell_s", "cooldown_s",
        "decisions"}
    assert set(doc["replicas"]) == {"rp", "r0", auto_id}

    for lg in logs.values():
        lg.close()
    paths = [lg.path for lg in logs.values()]
    merged = summarize_jsonl(paths)
    tl = merged["requests"][rid]
    whats = [e["what"] for e in tl]
    assert {"cluster_place", "cluster_handoff", "cluster_slot_migrate",
            "serve_finish"} <= set(whats)
    # the migration hop precedes the finish in the merged wall order
    assert whats.index("cluster_slot_migrate") < whats.index(
        "serve_finish")
    # ONE trace identity across every router hop, matching the Result
    tids = {e["detail"]["trace_id"] for e in tl
            if e["what"].startswith("cluster_")}
    assert tids == {res[rid].trace_id}
    # hop counters grow monotonically along the merged timeline
    hops = [e["detail"]["hop"] for e in tl if "hop" in e["detail"]]
    assert hops == sorted(hops) and len(set(hops)) == len(hops)
    text = format_request_timeline(merged, rid)
    assert "cluster_slot_migrate" in text
    assert "(+" in text                 # per-hop latency attribution

    # frozen trace-hop schemas: the cross-replica grep contract
    recs = _records(paths)
    assert _schemas(recs, "cluster_place") == {frozenset(
        {"ts", "event", "id", "replica", "attempt", "trace_id",
         "hop"})}
    assert _schemas(recs, "cluster_handoff") == {frozenset(
        {"ts", "event", "id", "replica", "prefix_tokens", "cached",
         "trace_id", "hop"})}
    assert _schemas(recs, "cluster_slot_migrate") == {frozenset(
        {"ts", "event", "id", "src", "dst", "trace_id", "hop"})}
    assert _schemas(recs, "cluster_scale_up") == {frozenset(
        {"ts", "event", "replica", "live"})}
    assert _schemas(recs, "cluster_drain") == {frozenset(
        {"ts", "event", "replica"})}
    assert _schemas(recs, "autoscale_decision") == {frozenset(
        {"ts", "event", "action", "reason", "live", "queued", "t"})}
    assert _schemas(recs, "cluster_prefix_publish") == {frozenset(
        {"ts", "event", "prefix_tokens", "nbytes"})}


# -- 2. merged fleet metrics + rollups --------------------------------------


def _series(reg, name):
    inst = reg.get(name)
    if inst is None:
        return {}
    return {tuple(sorted(labels.items())): val
            for labels, val in inst._series()}


def test_fleet_metrics_rollups_equal_per_replica_sums(devices, params):
    """The merged exposition carries every replica's series under a
    ``replica`` label, VERBATIM — and each fleet rollup equals the sum
    of those per-replica series in the same scrape. Both sides come
    from one registry snapshot, so the equality is exact, not
    approximately-concurrent."""
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps, registry=MetricsRegistry())
    rng = np.random.default_rng(3)
    # budget > window so decode spans several cycles: the first token
    # and the finish land in different cycles and the inter-token
    # latency samples exist deterministically, not by scheduler luck
    reqs = [Request(id=f"q{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + i)),
                    max_new_tokens=9) for i in range(4)]
    out = router.run([(0.0, q) for q in reqs])
    assert {r.status for r in out} == {"ok"}

    tele = ClusterTelemetry(router)
    merged = tele.merged_registry()
    # per-replica series survive the merge byte-for-byte, modulo the
    # added replica label
    for rep in reps:
        own = _series(rep.registry, "serve_requests_total")
        lifted = {
            tuple(kv for kv in key if kv[0] != "replica"): val
            for key, val in _series(merged,
                                    "serve_requests_total").items()
            if ("replica", rep.replica_id) in key}
        assert lifted == own and own, rep.replica_id
    # rollup == sum of the per-replica series in the SAME exposition
    qsum = sum(val for key, val
               in _series(merged, "serve_queue_depth").items()
               if any(k == "replica" for k, _ in key))
    assert merged.get("cluster_fleet_queue_depth") is not None
    assert _series(merged, "cluster_fleet_queue_depth") == {(): qsum}
    # ... and of the live per-replica scrapes at the same instant
    # (the fleet is idle, so the instant is stable)
    assert qsum == sum(
        rep.registry.get("serve_queue_depth").value() for rep in reps)
    # histogram state merges without re-observation: fleet count is
    # the sum of replica counts
    fleet_ttft = sum(
        st["count"] for _, st in
        merged.get("serve_ttft_seconds")._series())
    assert fleet_ttft == sum(
        st["count"] for rep in reps
        for _, st in rep.registry.get("serve_ttft_seconds")._series())
    assert fleet_ttft == len(reqs)
    # the router's own cluster_* series ride along unlabeled
    assert _series(merged, "cluster_placements_total")
    # the pooled decode-side tail joins the cluster rollup (ISSUE 20)
    s = router.summary()
    assert s["cluster_itl_ms_p95"] is not None
    assert s["cluster_ttft_ms_p95"] is not None
    text = tele.prometheus_text()
    assert 'replica="r0"' in text
    assert "cluster_fleet_queue_depth" in text


# -- 3. the fleet health surface --------------------------------------------


def test_fleet_healthz_embeds_replicas_and_compile_cache(devices,
                                                         params,
                                                         tmp_path):
    """Cluster-armed /healthz: every replica's own health document
    embedded verbatim, fleet aggregates, and the shared compile
    cache's counters — served over the same exporter whose non-cluster
    document keeps its historical shape."""
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps, registry=MetricsRegistry())
    # a little traffic so the health/metrics gauges have honest series
    out = router.run([(0.0, Request(id=f"h{i}", prompt=(1, 2, 3),
                                    max_new_tokens=2))
                      for i in range(2)])
    assert {r.status for r in out} == {"ok"}
    cache = CompileCache(tmp_path / "cc")
    tele = ClusterTelemetry(router, compile_cache=cache)
    doc = tele.health()
    assert doc["status"] == "ok"
    assert set(doc["replicas"]) == {"r0", "r1"}
    for rid, h in doc["replicas"].items():
        assert set(h) == set(reps[0].health()), rid
    assert set(doc["fleet"]) == {
        "replicas_live", "replicas_draining", "replicas_dead",
        "queue_depth", "load", "kv_pages_used", "kv_pages_total"}
    assert doc["fleet"]["replicas_live"] == 2
    assert doc["compile_cache"] == {"hits": 0, "misses": 0,
                                    "stores": 0}
    assert "autoscaler" not in doc      # absent when not armed
    assert "slo" not in doc

    # a dead replica degrades the fleet without hiding the survivors
    router.kill_replica("r1")
    doc = tele.health()
    assert doc["status"] == "degraded"
    assert doc["fleet"]["replicas_dead"] == 1
    assert doc["replicas"]["r1"]["state"] == "dead"

    with MetricsExporter(router.registry, port=0,
                         cluster=tele) as exp:
        with urllib.request.urlopen(exp.url + "/healthz") as resp:
            served = json.loads(resp.read())
        assert set(served) == set(doc)
        assert set(served["replicas"]) == {"r0", "r1"}
        with urllib.request.urlopen(exp.url + "/metrics") as resp:
            body = resp.read().decode()
        assert "cluster_fleet_queue_depth" in body
        assert 'replica="r0"' in body
    # the single-process surface is untouched: same keys as ever,
    # no fleet block
    solo = MetricsExporter(MetricsRegistry()).health()
    assert set(solo) == {"status", "last_tick_age_s", "queue_depth",
                         "slot_occupancy", "kv_pages_used",
                         "kv_pages_total", "brownout_stage"}


def test_fleet_slo_fires_on_skew_no_single_replica_sees(devices,
                                                        params):
    """The cluster-level SLO engine pools every replica's samples, so
    a fleet-wide breach SPREAD across replicas — each one below its
    own engine's min_samples — still fires. Each per-replica engine
    stays silent; the router's pooled engine breaches; the fleet
    health document says degraded while every embedded replica doc
    stays clean."""
    mk = lambda: SLOEngine(
        [SLO.latency("ttft", threshold_s=1e-9)], min_samples=10,
        registry=MetricsRegistry())
    reps = [_replica(params, f"r{i}", slo=mk()) for i in range(2)]
    fleet_slo = mk()
    router = Router(reps, slo=fleet_slo, registry=MetricsRegistry())
    reqs = [Request(id=f"w{i}", prompt=(1, 2, 3), max_new_tokens=2)
            for i in range(16)]
    out = router.run([(0.0, q) for q in reqs])
    assert {r.status for r in out} == {"ok"}
    fleet_slo.evaluate()
    assert fleet_slo.breached("ttft")   # 16 pooled samples: fires
    healths = {h["replica"]: h for h in router.healths()}
    # ~8 samples per replica: below min_samples, every engine silent
    assert not any(healths[f"r{i}"]["slo_breached"] for i in range(2))
    doc = ClusterTelemetry(router).health()
    assert doc["status"] == "degraded"
    assert doc["slo"]["ttft"]["breached"], doc["slo"]
    assert not any(h["slo_breached"] for h in doc["replicas"].values())


# -- 4. the anomaly watchdogs (unit: fakes drive each detector) -------------


class _FakeMetrics:
    def __init__(self):
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.compiles_observed = 0


class _FakeReplica:
    def __init__(self, rid):
        self.replica_id = rid
        self.state = "live"
        self.role = "mixed"
        self.server = type("S", (), {})()
        self.server.metrics = _FakeMetrics()
        self.breached = False

    def health(self):
        return {"slo_breached": self.breached}


class _FakeRouter:
    def __init__(self, reps):
        self.replicas = reps
        self.migrations = []
        self.slot_migrations = []
        self.registry = MetricsRegistry()
        self.rollout_canary = None


def test_watchdog_detectors_fire_once_and_stay_silent_when_clean(
        tmp_path):
    """Each detector: silent on a healthy fleet, fires exactly once on
    the transition into its injected fault (hysteresis), clears on
    recovery and can fire again, and every firing is one frozen-schema
    ``cluster_anomaly`` record plus a labeled counter bump."""
    reps = [_FakeReplica("a"), _FakeReplica("b")]
    fr = _FakeRouter(reps)
    wt = [0.0]
    log = JsonlLogger(tmp_path / "wd.jsonl")
    wd = ClusterWatchdog(
        fr, WatchdogConfig(window_s=5.0, accept_rate_floor=0.2,
                           accept_min_drafted=10,
                           compile_churn_limit=2,
                           migration_spike_limit=2),
        logger=log, clock=lambda: wt[0])

    def tick(dt=1.0):
        wt[0] += dt
        return wd.check()

    # clean fleet: quiet across the whole window
    for _ in range(6):
        assert tick() == []

    # 1. accept-rate collapse — healthy drafting first, then collapse
    reps[0].server.metrics.spec_drafted += 100
    reps[0].server.metrics.spec_accepted += 60
    assert tick() == []                 # rate 0.6: healthy
    reps[1].server.metrics.spec_drafted += 400
    reps[1].server.metrics.spec_accepted += 2
    fired = tick()
    assert [f["kind"] for f in fired] == ["accept_collapse"]
    assert fired[0]["replica"] is None  # fleet-wide kind
    reps[1].server.metrics.spec_drafted += 100
    assert tick() == []                 # still collapsed: no re-fire
    # recovery clears the alert; a fresh collapse fires again
    wt[0] += 10.0                       # rebase past the bad window
    wd.check()
    reps[0].server.metrics.spec_drafted += 100
    reps[0].server.metrics.spec_accepted += 90
    assert tick() == []
    reps[0].server.metrics.spec_drafted += 400
    fired = tick()                      # window rate 90/500 = 0.18
    assert [f["kind"] for f in fired] == ["accept_collapse"]

    # too little drafting neither fires nor clears: state HOLDS
    wt[0] += 10.0
    wd.check()
    reps[0].server.metrics.spec_drafted += 3
    assert tick() == []

    # 2. compile churn is per replica
    reps[1].server.metrics.compiles_observed += 5
    fired = tick()
    assert [(f["kind"], f["replica"]) for f in fired] == [
        ("compile_churn", "b")]

    # 3. migration spike is fleet-wide across both migration paths
    fr.migrations.extend([{}, {}])
    fr.slot_migrations.append({})
    fired = tick()
    assert [f["kind"] for f in fired] == ["migration_spike"]

    # 4. canary divergence: only when the canary ALONE is burning
    fr.rollout_canary = reps[1]
    reps[1].breached = True
    reps[0].breached = True             # baseline burning too: organic
    assert tick() == []
    reps[0].breached = False
    fired = tick()
    assert [(f["kind"], f["replica"]) for f in fired] == [
        ("canary_divergence", "b")]
    assert tick() == []                 # hysteresis
    fr.rollout_canary = None            # rollout closed: state clears
    tick()
    fr.rollout_canary = reps[1]         # the NEXT rollout fires fresh
    fired = tick()
    assert [f["kind"] for f in fired] == ["canary_divergence"]

    # frozen record schema + the labeled counter
    log.close()
    recs = _records([log.path])
    assert recs and _schemas(recs, "cluster_anomaly") == {frozenset(
        {"ts", "event", "kind", "replica", "value", "threshold",
         "window_s"})}
    counts = _series(fr.registry, "cluster_anomalies_total")
    assert counts[(("kind", "accept_collapse"),)] == 2
    assert counts[(("kind", "canary_divergence"),)] == 2
    assert counts[(("kind", "compile_churn"),)] == 1
    assert counts[(("kind", "migration_spike"),)] == 1
    assert len(wd.anomalies) == 6


def test_watchdog_config_validates():
    with pytest.raises(ValueError, match="window_s"):
        WatchdogConfig(window_s=0)
    with pytest.raises(ValueError, match="accept_rate_floor"):
        WatchdogConfig(accept_rate_floor=1.5)
    with pytest.raises(ValueError, match="accept_min_drafted"):
        WatchdogConfig(accept_min_drafted=0)
    with pytest.raises(ValueError, match="limits"):
        WatchdogConfig(compile_churn_limit=-1)


# -- 5. remaining trace-hop event schemas -----------------------------------


def test_canary_and_shed_events_carry_the_trace_schema(devices, params,
                                                       tmp_path):
    """The rollout-canary placement marker and the cluster-wide shed
    Result both ride the trace chain: frozen schemas, rid-joinable,
    trace_id-stamped — so `stats --request` shows WHY a request landed
    on a canary or never ran at all."""
    log = JsonlLogger(tmp_path / "router.jsonl")
    reps = [_replica(params, f"r{i}") for i in range(2)]
    router = Router(reps, logger=log, registry=MetricsRegistry())
    cid = router.start_rollout(params)
    assert cid in {"r0", "r1"}
    reqs = [Request(id=f"c{i}", prompt=(1, 2, 3), max_new_tokens=2)
            for i in range(4)]
    for q in reqs:
        assert router.submit(q)
    router.drain()
    router.finish_rollout()
    router.kill_replica("r0")
    router.kill_replica("r1")
    assert not router.submit(Request(id="lost", prompt=(1, 2),
                                     max_new_tokens=2))
    log.close()
    recs = _records([log.path])
    assert _schemas(recs, "cluster_canary") == {frozenset(
        {"ts", "event", "id", "replica", "trace_id", "hop"})}
    canaried = {r["id"] for r in recs
                if r.get("event") == "cluster_canary"}
    assert canaried <= {q.id for q in reqs} and canaried
    # every canary marker shares its request's placement trace_id
    by_rid = {}
    for r in recs:
        if r.get("event") == "cluster_place":
            by_rid[r["id"]] = r["trace_id"]
    for r in recs:
        if r.get("event") == "cluster_canary":
            assert r["trace_id"] == by_rid[r["id"]]
    assert _schemas(recs, "cluster_shed") == {frozenset(
        {"ts", "event", "id", "trace_id", "reason"})}
    shed = [r for r in recs if r.get("event") == "cluster_shed"]
    assert shed[0]["id"] == "lost"
    assert shed[0]["reason"] == "no_live_replica"
    base = {"ts", "event", "stage", "replica"}
    assert _schemas(recs, "cluster_rollout") <= {
        frozenset(base), frozenset(base | {"reason"})}
    assert _schemas(recs, "cluster_rollout")
