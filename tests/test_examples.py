"""The examples/ scripts must stay runnable — each is executed as a
subprocess exactly as the README tells users to run them (they
self-configure the virtual 8-device CPU pod)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES = sorted((Path(__file__).parent.parent / "examples").glob("*.py"))
# share the suite's persistent compilation cache (conftest.py) with the
# subprocesses so repeat runs skip the example models' compiles too —
# only where the cache is trustworthy (see conftest.PERSISTENT_CACHE_OK:
# 0.4.x XLA:CPU serves silently-wrong deserialized executables)
from conftest import PERSISTENT_CACHE_OK

_ENV = dict(os.environ)
if PERSISTENT_CACHE_OK:
    _ENV.update(
        JAX_COMPILATION_CACHE_DIR=str(Path(__file__).parent / ".jax_cache"),
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.5",
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="-1",
    )


@pytest.mark.parametrize("script", EXAMPLES, ids=lambda p: p.name)
def test_example_runs(script):
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, timeout=600, env=_ENV)
    assert r.returncode == 0, f"{script.name} failed:\n{r.stdout}\n{r.stderr}"
    assert r.stdout.strip(), f"{script.name} printed nothing"


def test_examples_exist():
    assert len(EXAMPLES) >= 3
