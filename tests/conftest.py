"""Test harness: fake an 8-device TPU pod with virtual CPU devices.

Must run before jax initializes — pytest imports conftest first, so setting
the env here is sufficient as long as no test module imports jax at
collection time before this file executes (pytest guarantees conftest.py
is imported before test modules).
"""

from idc_models_tpu import mesh as _meshlib

_meshlib.force_cpu_pod(8)

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
