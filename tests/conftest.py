"""Test harness: fake an 8-device TPU pod with virtual CPU devices.

Must run before jax initializes — pytest imports conftest first, so setting
the env here is sufficient as long as no test module imports jax at
collection time before this file executes (pytest guarantees conftest.py
is imported before test modules).
"""

import pathlib

from idc_models_tpu import mesh as _meshlib

_meshlib.force_cpu_pod(8)

import jax  # noqa: E402

# Persistent compilation cache: repeat suite runs skip recompiles (a
# VGG16 train-step compile drops ~1.6s -> ~0.3s; the suite is full of
# them). Keyed by HLO + compile options + jax version, so stale entries
# can't be served; the dir is gitignored.
#
# ONLY on newer jax (the top-level-shard_map API line): on 0.4.x
# XLA:CPU a DESERIALIZED cached executable of a donating jitted train
# step silently returns wrong outputs — first (cold) run correct,
# second (warm) run leaves updated params untouched (reproduced via
# test_freeze_machinery_applies: head delta 0.0316 cold, 0.0 from the
# cache hit). Correctness over speed: leave the cache off there.
PERSISTENT_CACHE_OK = hasattr(jax, "shard_map")
if PERSISTENT_CACHE_OK:
    jax.config.update("jax_compilation_cache_dir",
                      str(pathlib.Path(__file__).parent / ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
else:
    # actively DISABLE it: an ambient JAX_COMPILATION_CACHE_DIR in the
    # developer's shell would re-enable the broken cache behind the
    # guard (and test_examples.py copies os.environ into subprocesses)
    import os as _os

    for _var in ("JAX_COMPILATION_CACHE_DIR",
                 "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                 "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"):
        _os.environ.pop(_var, None)
    jax.config.update("jax_compilation_cache_dir", None)

import os  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


# ---------------------------------------------------------------------------
# Per-test duration budget (ISSUE 4 CI satellite)
#
# The tier-1 window is 870 s for the whole suite; one silently slow new
# test erodes it for everyone. Every test's call duration is recorded
# and printed in the terminal summary (so the tier-1 log carries the
# data), and a PASSED test that is not marked `slow` FAILS if its call
# exceeds the budget — mark it `slow` (excluded from tier-1) or split
# it. IDC_TEST_BUDGET_S overrides the 60 s default; 0 disables.
#
# Two defenses keep machine phase from turning into failures (the
# container's CPU throughput swings 2-4x on a minutes timescale — see
# tier1-timing-and-noise):
#  - the budget scales by a slowdown factor measured at session start
#    (a fixed numpy workload vs its fast-phase reference time), so 60 s
#    means "60 s on a nominal machine";
#  - pre-existing tests measured >= ~15 s on slow phases are
#    grandfathered at their current cost. The ratchet applies to
#    everything NEW.
# ---------------------------------------------------------------------------

TEST_BUDGET_S = float(os.environ.get("IDC_TEST_BUDGET_S", "60"))


def _machine_slowdown() -> float:
    """How much slower the machine is RIGHT NOW than the fast phase: a
    fixed f32 matmul workload vs its reference wall time (~0.15 s on
    this container's fast phases; ~0.3 s mid-phase, >0.5 s when slow).
    Clamped to >= 1 so a fast machine enforces the nominal budget.
    Measured once at session start AND re-measured when a test first
    exceeds the budget — the phase swings on a minutes timescale, so a
    session-start sample alone would mis-sentence a test that ran
    during a later slow phase."""
    import time as _time

    import numpy as _np

    a = _np.random.default_rng(0).normal(size=(512, 512))
    a = a.astype(_np.float32)
    t0 = _time.perf_counter()
    for _ in range(8):
        a = _np.tanh(a @ a.T * 1e-3)
    return max(1.0, (_time.perf_counter() - t0) / 0.15)


_SLOWDOWN = _machine_slowdown() if TEST_BUDGET_S > 0 else 1.0

BUDGET_GRANDFATHERED = {
    "tests/test_attention_model.py::test_attention_classifier_learns_zigzag",
    "tests/test_attention_model.py::"
    "test_attention_classifier_learns_on_2d_mesh",
    "tests/test_attention_model.py::"
    "test_remat_identical_values_and_grads[pallas]",
    "tests/test_attention_model.py::"
    "test_remat_identical_values_and_grads[jnp]",
    "tests/test_attention_model.py::"
    "test_residual_stream_stays_seq_sharded[contiguous]",
    "tests/test_attention_model.py::"
    "test_residual_stream_stays_seq_sharded[zigzag]",
    "tests/test_cli_e2e.py::test_cli_dense_cifar",
    "tests/test_cli_e2e.py::test_cli_fed_checkpoint_gate_and_resume",
    "tests/test_cli_e2e.py::test_cli_mobile",
    "tests/test_cli_e2e.py::test_cli_attention",
    "tests/test_cli_e2e.py::test_cli_secure_fed_paillier",
    "tests/test_cli_e2e.py::test_cli_vgg_two_phase",
    "tests/test_cli_e2e.py::test_cli_vgg_streamed",
    "tests/test_cli_e2e.py::test_cli_vgg_pretrained_weights",
    "tests/test_examples.py::test_example_runs[01_two_phase_vgg.py]",
    "tests/test_examples.py::test_example_runs[05_attention_classifier.py]",
    "tests/test_examples.py::test_example_runs[07_lm_train_and_generate.py]",
    "tests/test_examples.py::"
    "test_example_runs[08_serve_continuous_batching.py]",
    "tests/test_examples.py::test_example_runs[09_federated_faults.py]",
    "tests/test_faults.py::test_fault_plan_replays_bit_identically",
    "tests/test_feature_cache.py::"
    "test_two_phase_cached_matches_uncached_densenet",
    "tests/test_feature_cache.py::"
    "test_two_phase_cached_matches_uncached_mobilenet",
    "tests/test_feature_cache.py::test_two_phase_cached_matches_uncached",
    "tests/test_feature_cache.py::"
    "test_cached_phase2_resumes_and_survives_cache_toggle",
    "tests/test_feature_cache.py::test_densenet_split_composes_to_full",
    "tests/test_feature_cache.py::test_mobilenet_split_composes_to_full",
    "tests/test_federated.py::test_padded_dummy_clients_are_inert",
    "tests/test_federated.py::test_server_state_checkpoint_roundtrip",
    "tests/test_golden_learning.py::test_densenet_two_phase_learns_task",
    "tests/test_golden_learning.py::test_mobilenet_two_phase_learns_task",
    "tests/test_golden_learning.py::"
    "test_vgg16_two_phase_learns_task_from_pretrained",
    "tests/test_golden_learning.py::test_fedavg_learns_task",
    "tests/test_golden_learning.py::test_secure_fedavg_learns_task",
    "tests/test_lm.py::test_lm_learns_and_generates",
    "tests/test_loop.py::test_profile_trace_writes_tensorboard_artifact",
    "tests/test_models.py::test_densenet_stem_symmetric_padding",
    "tests/test_multihost.py::test_two_process_dp_step_agrees",
    "tests/test_ring_decode.py::test_batched_decode_rowwise_bit_parity",
    "tests/test_robust.py::test_byzantine_robustness_acceptance",
    "tests/test_secure.py::test_paillier_clients_full_protocol",
    "tests/test_zigzag.py::test_unrolled_ring_matches_full[zigzag-pallas]",
}

_durations: list[tuple[float, str]] = []


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if report.when != "call":
        return
    global _SLOWDOWN
    _durations.append((report.duration, report.nodeid))
    over_budget = (TEST_BUDGET_S > 0
                   and report.duration > TEST_BUDGET_S * _SLOWDOWN)
    if over_budget:
        # before sentencing, re-probe: the machine may have entered a
        # slower phase since the session-start calibration (probing
        # only on violations keeps the per-test overhead at zero)
        _SLOWDOWN = max(_SLOWDOWN, _machine_slowdown())
    effective = TEST_BUDGET_S * _SLOWDOWN
    if (over_budget and report.passed
            and report.duration > effective
            and "slow" not in item.keywords
            and report.nodeid not in BUDGET_GRANDFATHERED):
        report.outcome = "failed"
        report.longrepr = (
            f"{report.nodeid} exceeded the tier-1 per-test budget: "
            f"{report.duration:.1f}s > {effective:.0f}s "
            f"({TEST_BUDGET_S:.0f}s budget x {_SLOWDOWN:.2f} measured "
            f"machine slowdown). The suite shares an 870s window — "
            f"mark the test `slow` (excluded from tier-1), split it, "
            f"or shrink its workload. IDC_TEST_BUDGET_S overrides; "
            f"grandfathered legacy tests are listed in "
            f"tests/conftest.py.")


def pytest_terminal_summary(terminalreporter):
    if not _durations:
        return
    tr = terminalreporter
    tr.section("tier-1 per-test durations (conftest budget hook)")
    for dur, nodeid in sorted(_durations, reverse=True)[:15]:
        tr.write_line(f"{dur:8.2f}s  {nodeid}")
    total = sum(d for d, _ in _durations)
    effective = TEST_BUDGET_S * _SLOWDOWN
    over = sum(1 for d, _ in _durations if d > effective)
    tr.write_line(
        f"total {total:.1f}s across {len(_durations)} tests; "
        f"{over} over the effective {effective:.0f}s budget "
        f"({TEST_BUDGET_S:.0f}s x {_SLOWDOWN:.2f} machine slowdown; "
        f"IDC_TEST_BUDGET_S to override, slow marker to exempt)")
