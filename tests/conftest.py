"""Test harness: fake an 8-device TPU pod with virtual CPU devices.

Must run before jax initializes — pytest imports conftest first, so setting
the env here is sufficient as long as no test module imports jax at
collection time before this file executes (pytest guarantees conftest.py
is imported before test modules).
"""

import os

# Force CPU: the ambient environment may point JAX_PLATFORMS at a real
# (single) TPU chip; tests need the 8-device virtual pod instead. jax may
# already be preloaded into the interpreter, so set the platform through
# jax.config (env vars would be read too late) — the XLA_FLAGS below are
# still honored because the CPU backend is only created on first use.
os.environ["JAX_PLATFORMS"] = "cpu"

from idc_models_tpu import mesh as _meshlib  # noqa: E402

_meshlib.force_host_devices(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
