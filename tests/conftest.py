"""Test harness: fake an 8-device TPU pod with virtual CPU devices.

Must run before jax initializes — pytest imports conftest first, so setting
the env here is sufficient as long as no test module imports jax at
collection time before this file executes (pytest guarantees conftest.py
is imported before test modules).
"""

import pathlib

from idc_models_tpu import mesh as _meshlib

_meshlib.force_cpu_pod(8)

import jax  # noqa: E402

# Persistent compilation cache: repeat suite runs skip recompiles (a
# VGG16 train-step compile drops ~1.6s -> ~0.3s; the suite is full of
# them). Keyed by HLO + compile options + jax version, so stale entries
# can't be served; the dir is gitignored.
#
# ONLY on newer jax (the top-level-shard_map API line): on 0.4.x
# XLA:CPU a DESERIALIZED cached executable of a donating jitted train
# step silently returns wrong outputs — first (cold) run correct,
# second (warm) run leaves updated params untouched (reproduced via
# test_freeze_machinery_applies: head delta 0.0316 cold, 0.0 from the
# cache hit). Correctness over speed: leave the cache off there.
PERSISTENT_CACHE_OK = hasattr(jax, "shard_map")
if PERSISTENT_CACHE_OK:
    jax.config.update("jax_compilation_cache_dir",
                      str(pathlib.Path(__file__).parent / ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
else:
    # actively DISABLE it: an ambient JAX_COMPILATION_CACHE_DIR in the
    # developer's shell would re-enable the broken cache behind the
    # guard (and test_examples.py copies os.environ into subprocesses)
    import os as _os

    for _var in ("JAX_COMPILATION_CACHE_DIR",
                 "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                 "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"):
        _os.environ.pop(_var, None)
    jax.config.update("jax_compilation_cache_dir", None)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
