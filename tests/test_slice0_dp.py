"""Slice 0 end-to-end: small CNN + DP train step over an 8-device mesh.

Asserts (a) the mesh/jit/sharding machinery compiles and runs, (b) loss
decreases on learnable synthetic data, (c) 8-device data-parallel training
is numerically equivalent to single-device training on the same global
batch (the defining property of MirroredStrategy-style DP, reference D1).
"""

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import (
    create_train_state, jit_data_parallel, make_eval_step, make_train_step,
    replicate, rmsprop, shard_batch,
)
from idc_models_tpu.train.losses import binary_cross_entropy


def _setup(mesh):
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    state = create_train_state(model, opt, jax.random.key(0))
    train_step = make_train_step(model, opt, binary_cross_entropy)
    return model, opt, state, train_step


def test_loss_decreases_on_8_device_mesh(devices):
    mesh = meshlib.data_mesh(8)
    model, opt, state, train_step = _setup(mesh)
    step = jit_data_parallel(train_step, mesh)
    imgs, labels = synthetic.make_idc_like(256, size=10, seed=0)
    state = replicate(mesh, state)

    losses = []
    key = jax.random.key(42)
    for i in range(30):
        key, sub = jax.random.split(key)
        x, y = shard_batch(mesh, imgs, labels)
        state, m = step(state, x, y, sub)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert int(state.step) == 30


def test_dp_equals_single_device(devices):
    imgs, labels = synthetic.make_idc_like(64, size=10, seed=1)
    key = jax.random.key(7)

    def run(n_dev):
        mesh = meshlib.data_mesh(n_dev)
        model, opt, state, train_step = _setup(mesh)
        step = jit_data_parallel(train_step, mesh)
        state = replicate(mesh, state)
        k = key
        for _ in range(5):
            k, sub = jax.random.split(k)
            x, y = shard_batch(mesh, imgs, labels)
            state, m = step(state, x, y, sub)
        return jax.device_get(state.params), float(m["loss"])

    p8, l8 = run(8)
    p1, l1 = run(1)
    np.testing.assert_allclose(l8, l1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(p1)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_eval_step(devices):
    mesh = meshlib.data_mesh(8)
    model, opt, state, _ = _setup(mesh)
    eval_step = jit_data_parallel(make_eval_step(model, binary_cross_entropy),
                                  mesh, donate_state=False)
    imgs, labels = synthetic.make_idc_like(64, size=10, seed=2)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)
    m = eval_step(state, x, y)
    assert 0.0 <= float(m["accuracy"]) <= 1.0
    assert np.isfinite(float(m["loss"]))
