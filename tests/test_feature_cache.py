"""Frozen-backbone feature cache (train/feature_cache.py): split
correctness, plan fallbacks, and cached-vs-uncached phase-2 equivalence
on the flagship VGG16 config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.models import core, registry
from idc_models_tpu.models.vgg import KERAS_LAYER_INDEX, vgg16, vgg16_backbone
from idc_models_tpu.train import TwoPhaseConfig, two_phase_fit
from idc_models_tpu.train import feature_cache as fc


def test_split_sequential_composes_to_full():
    bb = vgg16_backbone()
    v = bb.init(jax.random.key(0))
    x = jnp.asarray(
        np.random.default_rng(0).random((2, 50, 50, 3), np.float32))
    full, _ = bb.apply(v.params, v.state, x, train=False)
    prefix, suffix = core.split_sequential(bb, "block5_conv1")
    pk = [k for k, _ in prefix.children]
    sk = [k for k, _ in suffix.children]
    assert pk[-1] == "block4_pool" and sk[0] == "block5_conv1"
    h, _ = prefix.apply({k: v.params[k] for k in pk if k in v.params},
                        {}, x, train=False)
    out, _ = suffix.apply({k: v.params[k] for k in sk if k in v.params},
                          {}, h, train=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(full))


def test_split_unknown_key_raises():
    bb = vgg16_backbone()
    with pytest.raises(KeyError, match="nope"):
        core.split_sequential(bb, "nope")
    # non-contiguous / reordered subsets are rejected, empty is identity
    with pytest.raises(ValueError, match="contiguous"):
        core.subsequence(bb, ["block3_conv1", "block1_conv1"])
    with pytest.raises(ValueError, match="contiguous"):
        core.subsequence(bb, ["block1_conv1", "block3_conv1"])
    empty = core.subsequence(bb, [])
    x = jnp.ones((1, 4, 4, 3))
    out, _ = empty.apply({}, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_plan_vgg_boundary_and_fallbacks():
    model = vgg16(1)
    plan = fc.plan_feature_cache(model, KERAS_LAYER_INDEX, 15, 512, 1)
    assert plan is not None and plan.boundary == "block5_conv1"
    assert plan.suffix_keys[0] == "block5_conv1"
    # fine_tune_at below every index: nothing frozen -> no plan
    assert fc.plan_feature_cache(model, KERAS_LAYER_INDEX, 0, 512, 1) is None
    # fine_tune_at above every index: whole backbone cached, head trains
    plan_all = fc.plan_feature_cache(model, KERAS_LAYER_INDEX, 10_000,
                                     512, 1)
    assert plan_all is not None and plan_all.boundary is None
    assert plan_all.suffix_keys == ()
    # a model without children metadata is not splittable
    small = registry.get_model("small_cnn").build(1, 3)
    assert fc.plan_feature_cache(small, {}, 0, 8, 1) is None


def _assert_split_composes(bb, fine_tune_at, layer_index, image_size):
    """Shared check for unit splitters: prefix∘suffix == full forward,
    prefix fully frozen. Returns (prefix, suffix) for extra assertions."""
    v = bb.init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(0).random(
        (2, image_size, image_size, 3), np.float32))
    full, _ = bb.apply(v.params, v.state, x, train=False)
    split = bb.splitter(fine_tune_at)
    assert split is not None
    prefix, suffix = split
    assert all(layer_index[n] < fine_tune_at for n in prefix.layer_names)
    sub = lambda tree, names: {k: tree[k] for k in names if k in tree}
    h, _ = prefix.apply(sub(v.params, prefix.layer_names),
                        sub(v.state, prefix.layer_names), x, train=False)
    out, _ = suffix.apply(sub(v.params, suffix.layer_names),
                          sub(v.state, suffix.layer_names), h, train=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(full))
    return prefix, suffix


def test_mobilenet_split_composes_to_full():
    """The splitter's prefix∘suffix must equal the full backbone forward
    (residual adds live entirely inside units, so any unit edge works)."""
    from idc_models_tpu.models.mobilenet import (
        KERAS_LAYER_INDEX as MNV2_INDEX, mobilenet_v2_backbone,
    )

    bb = mobilenet_v2_backbone(3, bn_frozen_below=100)
    prefix, suffix = _assert_split_composes(bb, 100, MNV2_INDEX, 50)
    # fine_tune_at=100 lands inside block 11: prefix = stem + blocks 1-10
    assert "block_10_project" in prefix.layer_names
    assert "block_11_expand" in suffix.layer_names
    # boundary below everything -> no frozen prefix -> no split
    assert bb.splitter(0) is None


def test_mobilenet_plan(devices):
    from idc_models_tpu.models.mobilenet import (
        KERAS_LAYER_INDEX as MNV2_INDEX, mobilenet_v2,
    )

    model = mobilenet_v2(1, bn_frozen_below=100)
    plan = fc.plan_feature_cache(model, MNV2_INDEX, 100, 1280, 1)
    assert plan is not None and plan.boundary == "block_11_expand"
    assert "Conv_1" in plan.suffix_keys


def test_two_phase_cached_matches_uncached_mobilenet(devices):
    """BN-bearing backbone: frozen-prefix BN runs in inference mode, so
    the cache is exact there too; live-suffix BN batch stats see the
    same batches either way."""
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(40, size=50, seed=0)
    train = ArrayDataset(imgs[:24], labels[:24])
    val = ArrayDataset(imgs[24:], labels[24:])
    kw = dict(lr=1e-4, epochs=1, fine_tune_epochs=1, batch_size=8,
              eval_steps=1, seed=0)

    r_plain = two_phase_fit("mobilenet_v2", 1, train, val, mesh,
                            TwoPhaseConfig(**kw))
    r_cached = two_phase_fit("mobilenet_v2", 1, train, val, mesh,
                             TwoPhaseConfig(cache_features=True, **kw))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(r_plain.state.params),
        jax.device_get(r_cached.state.params))
    # BN moving stats of the live suffix must track identically too
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(r_plain.state.model_state),
        jax.device_get(r_cached.state.model_state))


def test_densenet_split_composes_to_full():
    """Dense-concat topology: a dense layer is h -> concat(h, f(h)), so
    unit edges are valid split points; ft=150 lands inside conv4_block2."""
    from idc_models_tpu.models.densenet import (
        KERAS_LAYER_INDEX as DN_INDEX, densenet201_backbone,
    )

    bb = densenet201_backbone(3, bn_frozen_below=150)
    _, suffix = _assert_split_composes(bb, 150, DN_INDEX, 32)
    assert "conv4_block2_1_conv" in suffix.layer_names


def test_two_phase_cached_matches_uncached_densenet(devices):
    """Phase 2 only (epochs=0 skips phase 1 to keep this test fast):
    cached and uncached fine-tuning of DenseNet201 must coincide."""
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(24, size=32, seed=0)
    labels = (np.arange(24) % 10).astype(np.int32)
    train = ArrayDataset(imgs[:16], labels[:16])
    val = ArrayDataset(imgs[16:], labels[16:])
    kw = dict(lr=1e-4, epochs=0, fine_tune_epochs=1, batch_size=8,
              eval_steps=1, seed=0)

    r_plain = two_phase_fit("densenet201", 10, train, val, mesh,
                            TwoPhaseConfig(**kw))
    r_cached = two_phase_fit("densenet201", 10, train, val, mesh,
                             TwoPhaseConfig(cache_features=True, **kw))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(r_plain.state.params),
        jax.device_get(r_cached.state.params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(r_plain.state.model_state),
        jax.device_get(r_cached.state.model_state))


def test_cached_phase2_resumes_and_survives_cache_toggle(devices, tmp_path):
    """--cache-features + --resumable: the suffix fit checkpoints and a
    rerun restores it (same end state); toggling the cache OFF afterwards
    changes the checkpoint fingerprint (suffix vs full trees), so the
    stale checkpoint is ignored with a warning instead of crashing."""
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(32, size=50, seed=0)
    train = ArrayDataset(imgs[:24], labels[:24])
    val = ArrayDataset(imgs[24:], labels[24:])
    kw = dict(lr=1e-3, epochs=0, fine_tune_epochs=1, batch_size=8,
              eval_steps=1, seed=0)
    d = str(tmp_path / "ck")

    r1 = two_phase_fit("vgg16", 1, train, val, mesh,
                       TwoPhaseConfig(cache_features=True, **kw),
                       checkpoint_dir=d)
    r2 = two_phase_fit("vgg16", 1, train, val, mesh,
                       TwoPhaseConfig(cache_features=True, **kw),
                       checkpoint_dir=d)
    for a, b in zip(jax.tree.leaves(jax.device_get(r1.state.params)),
                    jax.tree.leaves(jax.device_get(r2.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    with pytest.warns(UserWarning, match="different run"):
        two_phase_fit("vgg16", 1, train, val, mesh,
                      TwoPhaseConfig(cache_features=False, **kw),
                      checkpoint_dir=d)


def test_two_phase_cached_matches_uncached(devices):
    """The headline guarantee: phase 2 on cached features reproduces the
    uncached phase-2 training trajectory (same seeds, no rng consumers in
    the live path)."""
    mesh = meshlib.data_mesh(8)
    imgs, labels = synthetic.make_idc_like(48, size=50, seed=0)
    train = ArrayDataset(imgs[:32], labels[:32])
    val = ArrayDataset(imgs[32:], labels[32:])
    kw = dict(lr=1e-3, epochs=1, fine_tune_epochs=1, batch_size=8,
              eval_steps=1, seed=0)

    r_plain = two_phase_fit("vgg16", 1, train, val, mesh,
                            TwoPhaseConfig(**kw))
    r_cached = two_phase_fit("vgg16", 1, train, val, mesh,
                             TwoPhaseConfig(cache_features=True, **kw))

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(r_plain.state.params),
        jax.device_get(r_cached.state.params))
    np.testing.assert_allclose(r_plain.history_fine["loss"],
                               r_cached.history_fine["loss"], rtol=1e-4)
    np.testing.assert_allclose(r_plain.history_fine["val_loss"],
                               r_cached.history_fine["val_loss"], rtol=1e-4)
