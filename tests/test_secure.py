"""Secure aggregation: mask cancellation, Paillier round-trips, and the
secure FedAvg round (SURVEY.md §4: "masks cancel: psum of masked == psum
of plain; Paillier enc→agg→dec == plain mean")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.compat import shard_map
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import initialize_server, make_fedavg_round
from idc_models_tpu.models import small_cnn
from idc_models_tpu.secure import (
    dequantize, first_fraction_selection, make_secure_fedavg_round,
    pairwise_mask, quantize,
)
from idc_models_tpu.secure.fedavg import PaillierClient, PaillierServer
from idc_models_tpu.secure.paillier import generate_paillier_keypair
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N_CLIENTS = 8


def test_masks_cancel_exactly():
    """Sum over all clients of the pairwise masks is exactly zero."""
    key = jax.random.key(7)
    shape = (33, 5)
    total = jnp.zeros(shape, jnp.int32)
    for i in range(N_CLIENTS):
        total = total + pairwise_mask(key, jnp.int32(i), N_CLIENTS, shape)
    np.testing.assert_array_equal(np.asarray(total), 0)


def test_masked_psum_equals_plain_psum():
    """psum of masked quantized updates == psum of plain ones, bit-exact,
    while each individual masked contribution is (pseudo)random."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    key = jax.random.key(3)
    vals = np.random.default_rng(0).normal(size=(N_CLIENTS, 17)).astype(
        np.float32)

    def body(x):
        cid = collectives.axis_index(meshlib.CLIENT_AXIS)
        q = quantize(x[0])
        m = pairwise_mask(key, cid, N_CLIENTS, q.shape)
        masked_sum = collectives.psum(q + m, meshlib.CLIENT_AXIS)
        plain_sum = collectives.psum(q, meshlib.CLIENT_AXIS)
        return masked_sum, plain_sum, (q + m)[None]

    from jax.sharding import PartitionSpec as P
    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(meshlib.CLIENT_AXIS),
        out_specs=(P(), P(), P(meshlib.CLIENT_AXIS)), check_vma=False))
    masked_sum, plain_sum, contributions = f(vals)
    np.testing.assert_array_equal(np.asarray(masked_sum),
                                  np.asarray(plain_sum))
    # each device's masked contribution differs from its plain quantized
    # update. NOTE: this is a simulation-level property only — the round
    # key that derives the pairwise masks is held by the driver, so a
    # party with that key could regenerate the masks (masking.py
    # docstring; reference quirk Q9 keeps both Paillier keys global too).
    q_plain = np.asarray(quantize(jnp.asarray(vals)))
    assert not np.array_equal(np.asarray(contributions), q_plain)
    # and the dequantized mean matches the true mean to quantization error
    mean = np.asarray(dequantize(masked_sum, count=N_CLIENTS))
    np.testing.assert_allclose(mean, vals.mean(0), atol=2e-6)


def test_quantize_roundtrip():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(100,)) * 5)
    back = dequantize(quantize(x))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-6)


def test_dequantize_keeps_resolution_for_large_sums():
    """Sums past 2^24 (reachable with clip 64, scale 20, 8 clients) must
    not lose low bits: the split evaluation matches a float64 reference
    exactly for power-of-two counts (one rounding, at the result)."""
    s = 20
    q_np = np.asarray([2**24 + 1, -(2**24 + 1), 2**29 + 3, (1 << 31) - 1,
                       -(1 << 31), 12345, 0], np.int64)
    got = np.asarray(dequantize(jnp.asarray(q_np, jnp.int32), s, count=8))
    want = (q_np.astype(np.float64) / 2**s / 8).astype(np.float32)
    np.testing.assert_array_equal(got, want)
    # non-power-of-two count: one extra rounding, still ~ulp accurate
    got3 = np.asarray(dequantize(jnp.asarray(q_np, jnp.int32), s, count=3))
    np.testing.assert_allclose(
        got3, (q_np.astype(np.float64) / 2**s / 3).astype(np.float32),
        rtol=1e-7)


def test_paillier_exponent_gap_overflow_raises(keypair):
    """Aligning exponents across a huge magnitude gap would wrap the
    mantissa mod n and decrypt to garbage; it must raise instead."""
    pub, _ = keypair
    big = pub.encrypt(1e100)
    tiny = pub.encrypt(1e-100)
    with pytest.raises(ValueError, match="overflow"):
        _ = big + tiny
    # scalar multiplication grows the tracked mantissa bound (106 bits
    # here); a fixed 53-bit-mantissa assumption would wave this through
    # and the sum would wrap mod n and decrypt to garbage
    a = pub.encrypt(1e100) * 0.3
    b = pub.encrypt(1e-30) * 0.7
    with pytest.raises(ValueError, match="overflow"):
        _ = a + b
    # ordinary same-scale arithmetic is untouched by the guard
    _ = pub.encrypt(1e10) + pub.encrypt(1e-10) * 0.5


def test_quantize_clips_instead_of_wrapping():
    from idc_models_tpu.secure import choose_scale_bits

    big = jnp.asarray([1e9, -1e9, 10.0])
    q = quantize(big, 20, clip_abs=64.0)
    back = dequantize(q, 20)
    np.testing.assert_allclose(np.asarray(back), [64.0, -64.0, 10.0],
                               atol=1e-5)
    # headroom budget: sum of n fully saturated values must fit int32
    # STRICTLY (2^31 exactly would wrap to INT32_MIN)
    for n in (2, 8, 32, 1024):
        bits = choose_scale_bits(n, 64.0)
        assert (2.0 ** bits) * 64.0 * n <= 2 ** 31 - 1
    assert choose_scale_bits(8, 64.0) <= 21


def test_first_fraction_selection():
    tree = {"a": 1, "b": {"c": 2, "d": 3}, "e": 4}
    sel = first_fraction_selection(tree, 0.5)
    flags = jax.tree.leaves(sel)
    assert flags == [True, True, False, False]  # int(4*0.5)=2
    assert jax.tree.leaves(first_fraction_selection(tree, 0.0)) == [False] * 4
    assert jax.tree.leaves(first_fraction_selection(tree, 1.0)) == [True] * 4


def test_first_fraction_selection_layer_order():
    """With a model's layer_names, "first N tensors" follows Keras
    get_weights() order (layer creation order, kernel before bias), not
    jax's alphabetical flatten (secure_fed_model.py:115-121 parity)."""
    model = small_cnn(10, 3, 1)
    params = model.init(jax.random.key(0)).params
    # small_cnn layer order: conv1 -> fc1 -> head; get_weights() order is
    # conv1/kernel, conv1/bias, fc1/kernel, fc1/bias, head/kernel, head/bias.
    sel = first_fraction_selection(params, 0.5, model.layer_names)
    assert sel == {
        "conv1": {"kernel": True, "bias": True},
        "fc1": {"kernel": True, "bias": False},
        "head": {"kernel": False, "bias": False},
    }
    # alphabetical order would instead have protected conv1/bias,
    # conv1/kernel, fc1/bias — a different set
    sel_flat = first_fraction_selection(params, 0.5)
    assert sel_flat["fc1"] == {"kernel": False, "bias": True}


def test_first_fraction_selection_nested_classifier():
    """classifier() models rank backbone layers in creation order via
    dotted layer_names (not alphabetically), head last."""
    from idc_models_tpu.models import core

    backbone = core.sequential(
        [core.conv2d(3, 4, 3, name="z_first"),   # alphabetically LAST
         core.conv2d(4, 4, 3, name="a_second")],  # alphabetically FIRST
        name="bb")
    model = core.classifier(backbone, 4, 1)
    assert model.layer_names == ("backbone.z_first", "backbone.a_second",
                                 "head")
    params = model.init(jax.random.key(0)).params
    # first 3 of 6 tensors: z_first kernel+bias, a_second kernel
    sel = first_fraction_selection(params, 0.5, model.layer_names)
    assert sel == {
        "backbone": {"z_first": {"kernel": True, "bias": True},
                     "a_second": {"kernel": True, "bias": False}},
        "head": {"kernel": False, "bias": False},
    }


@pytest.fixture(scope="module")
def keypair():
    return generate_paillier_keypair(n_length=512)


class TestPaillier:
    def test_roundtrip(self, keypair):
        pub, priv = keypair
        for v in [0.0, 1.5, -2.75, 1e-8, -1e8, 123456.789]:
            assert priv.decrypt(pub.encrypt(v)) == pytest.approx(v, rel=1e-12)

    def test_homomorphic_add(self, keypair):
        pub, priv = keypair
        a, b = 3.25, -1.125
        s = pub.encrypt(a) + pub.encrypt(b)
        assert priv.decrypt(s) == pytest.approx(a + b, rel=1e-12)

    def test_scalar_mul_div(self, keypair):
        pub, priv = keypair
        c = pub.encrypt(7.5) * 0.125
        assert priv.decrypt(c) == pytest.approx(0.9375, rel=1e-9)
        d = pub.encrypt(10.0) / 8
        assert priv.decrypt(d) == pytest.approx(1.25, rel=1e-9)

    def test_ciphertext_mean_equals_plain_mean(self, keypair):
        pub, priv = keypair
        vals = [0.5, -1.5, 2.25, 3.0]
        enc = [pub.encrypt(v) for v in vals]
        acc = enc[0]
        for e in enc[1:]:
            acc = acc + e
        mean = acc / len(vals)
        assert priv.decrypt(mean) == pytest.approx(
            sum(vals) / len(vals), rel=1e-9)


def _client_data(n_per_client=32, seed=0):
    imgs, labels = synthetic.make_idc_like(n_per_client * N_CLIENTS, size=10,
                                           seed=seed)
    return partition_clients(ArrayDataset(imgs, labels), N_CLIENTS, iid=True,
                             seed=seed)


def test_secure_round_matches_plain_round(devices):
    """percent=1.0 secure round == plain unweighted FedAvg round up to
    quantization error (same rng, same local training)."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data()
    rng = jax.random.key(11)

    server_a = initialize_server(model, jax.random.key(0))
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=1.0,
        local_epochs=1, batch_size=16)
    sa, ma = secure_rnd(server_a, imgs, labels, rng)

    server_b = initialize_server(model, jax.random.key(0))
    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, mb = plain_rnd(server_b, imgs, labels,
                       np.ones((N_CLIENTS,), np.float32), rng)

    for a, b in zip(jax.tree.leaves(jax.device_get(sa.params)),
                    jax.tree.leaves(jax.device_get(sb.params))):
        np.testing.assert_allclose(a, b, atol=3e-6)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-5)


def test_secure_round_recovers_diverged_client(devices):
    """Failure recovery on the masked path, where a client cannot simply
    be dropped (its pairwise masks would stay uncancelled): the diverged
    client's update is replaced with the incoming global weights before
    masking. Expected aggregate = (7 healthy updates + old weights) / 8;
    the healthy updates come from the plain round with the dead client
    auto-dropped (identical rng derivation, proven by
    test_secure_round_matches_plain_round)."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=17)
    poisoned = np.array(imgs)
    poisoned[3] = np.nan
    rng = jax.random.key(23)

    server = initialize_server(model, jax.random.key(0))
    old_params = jax.device_get(server.params)
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=1.0,
        local_epochs=1, batch_size=16)
    sa, ma = secure_rnd(server, poisoned, labels, rng)
    assert int(ma["clients_recovered"]) == 1
    assert np.isfinite(float(ma["loss"]))
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(sa.params)))

    # healthy-only mean via the plain round's failure detection
    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, mb = plain_rnd(initialize_server(model, jax.random.key(0)),
                       poisoned, labels, np.ones((N_CLIENTS,), np.float32),
                       rng)
    for a, healthy_mean, old in zip(
            jax.tree.leaves(jax.device_get(sa.params)),
            jax.tree.leaves(jax.device_get(sb.params)),
            jax.tree.leaves(old_params)):
        want = (healthy_mean * (N_CLIENTS - 1) + old) / N_CLIENTS
        np.testing.assert_allclose(a, want, atol=5e-6)
    # metrics average only the clients that actually trained
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-5)

    # recovery can be disabled: the diverged client then poisons the
    # masked aggregate (why the default is on)
    rnd_off = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=0.5,
        local_epochs=1, batch_size=16, recover_nonfinite=False)
    s_off, _ = rnd_off(initialize_server(model, jax.random.key(0)),
                       poisoned, labels, rng)
    assert not all(np.all(np.isfinite(l))
                   for l in jax.tree.leaves(jax.device_get(s_off.params)))


def test_secure_round_layout_invariant(devices):
    """k clients per device: the same 8 clients on an 8-device mesh
    (k=1), a 4-device mesh (k=2), and a 1-device mesh (k=8) produce the
    same aggregate — the protected int32 path bit-for-bit (mod-2^32
    addition is layout-independent), the f32 path to fp tolerance.

    Skipped where the BACKEND itself is not layout-deterministic for
    the local-training program shape (see tests/_layout_probe.py): the
    divergence is in the clients' LOCAL training lowering, upstream of
    everything the secure protocol adds."""
    from _layout_probe import LAYOUT_SKIP_REASON, layout_invariant

    if not layout_invariant():
        pytest.skip(LAYOUT_SKIP_REASON)
    model = small_cnn(10, 3, 1)
    ci, cl = _client_data(seed=13)
    rng = jax.random.key(21)

    def run(n_dev, impl="threefry"):
        mesh = meshlib.client_mesh(n_dev)
        server = initialize_server(model, jax.random.key(0))
        rnd = make_secure_fedavg_round(
            model, rmsprop(1e-3), binary_cross_entropy, mesh, percent=0.5,
            local_epochs=1, batch_size=16, mask_impl=impl)
        server, m = rnd(server, ci, cl, rng)
        return jax.device_get(server.params), float(m["loss"])

    p8, l8 = run(8)
    p4, l4 = run(4)
    p1, l1 = run(1)
    # the pallas impl's masks differ but cancel identically, so even a
    # k=2 pallas layout must land on the same aggregate (exercises the
    # per-client kernel loop with k > 1)
    p4p, l4p = run(4, impl="pallas")
    for ref in (p4, p1, p4p):
        for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(ref)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose([l4, l1, l4p], l8, rtol=1e-5)
    # a non-dividing layout pads the client axis with mask-participating
    # dummy clients and runs on the FULL mesh — same aggregate (8 real
    # clients + 1 dummy over 3 devices)
    mesh3 = meshlib.client_mesh(3)
    rnd3 = make_secure_fedavg_round(
        model, rmsprop(1e-3), binary_cross_entropy, mesh3, percent=0.5,
        local_epochs=1, batch_size=16)
    s3, m3 = rnd3(initialize_server(model, jax.random.key(0)), ci, cl, rng)
    for a, b in zip(jax.tree.leaves(p8),
                    jax.tree.leaves(jax.device_get(s3.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(m3["loss"]), l8, rtol=1e-5)


def test_secure_round_full_mesh_for_any_client_count(devices):
    """VERDICT r2 #6: 10 clients on an 8-device mesh must use all 8
    devices (6 mask-participating dummies, k=2) and produce the
    BIT-IDENTICAL aggregate to the same 10 clients on the 5-device mesh
    `largest_dividing_mesh` would have picked — dummies contribute
    exact zeros to the int32 sum and the divisor stays 10."""
    n_clients = 10
    model = small_cnn(10, 3, 1)
    imgs, labels = synthetic.make_idc_like(n_clients * 16, size=10, seed=5)
    ci = imgs.reshape(n_clients, 16, 10, 10, 3)
    cl = labels.reshape(n_clients, 16)
    rng = jax.random.key(31)

    def run(n_dev):
        mesh = meshlib.client_mesh(n_dev)
        server = initialize_server(model, jax.random.key(0))
        # percent=1.0: EVERY tensor rides the masked int32 path, so the
        # whole aggregate must be bit-identical across layouts
        rnd = make_secure_fedavg_round(
            model, rmsprop(1e-3), binary_cross_entropy, mesh, percent=1.0,
            local_epochs=1, batch_size=16)
        server, m = rnd(server, ci, cl, rng)
        return jax.device_get(server.params), float(m["loss"])

    assert meshlib.largest_dividing_mesh(n_clients, 8) == 5
    p8, l8 = run(8)   # pads to 16 client slots over all 8 devices
    p5, l5 = run(5)   # exact fit, no dummies
    for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(p5)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(l8, l5, rtol=1e-6)


def test_mobilenet_selection_follows_keras_order():
    """Zoo backbones carry layer_names, so percent-selection follows the
    Keras get_weights() enumeration (VERDICT r1 weak #4): creation order
    with kernel -> scale -> bias within a layer, head last."""
    from idc_models_tpu.models.mobilenet import mobilenet_v2
    from idc_models_tpu.secure.masking import leaf_paths, ranked_indices

    model = mobilenet_v2(1)
    assert model.layer_names[0] == "backbone.Conv1"
    assert model.layer_names[-1] == "head"
    shapes = jax.eval_shape(lambda: dict(p=model.init(jax.random.key(0))
                                         .params))["p"]
    paths = leaf_paths(shapes)
    ordered = [paths[i] for i in ranked_indices(paths, model.layer_names)]
    assert ordered[0] == ("backbone", "Conv1", "kernel")
    assert ordered[1] == ("backbone", "bn_Conv1", "scale")
    assert ordered[2] == ("backbone", "bn_Conv1", "bias")
    assert ordered[3] == ("backbone", "expanded_conv_depthwise", "kernel")
    assert ordered[-2:] == [("head", "kernel"), ("head", "bias")]
    # densenet too: first parameterized layer is conv1_conv
    from idc_models_tpu.models.densenet import densenet201

    dn = densenet201(10)
    assert dn.layer_names[0] == "backbone.conv1_conv"
    assert dn.layer_names[-1] == "head"


def _bn_cnn():
    """Tiny BN-bearing classifier with a hand-checkable get_weights()
    enumeration: c1(k,b) b1(scale,bias,mean,var) c2(k,b) b2(...) head(k,b)
    = 14 tensors."""
    from idc_models_tpu.models import core

    backbone = core.sequential(
        [core.conv2d(3, 4, 3, name="c1"),
         core.batch_norm(4, name="b1"),
         core.relu(name="r1"),
         core.conv2d(4, 4, 3, name="c2"),
         core.batch_norm(4, name="b2"),
         core.relu(name="r2")],
        name="bb")
    return core.classifier(backbone, 4, 1)


def _protected_paths(params, state, percent, layer_names):
    from idc_models_tpu.secure import first_fraction_selection_weights
    from idc_models_tpu.secure.masking import leaf_paths

    p_flags, s_flags = first_fraction_selection_weights(
        params, state, percent, layer_names)
    return ({p for p, f in zip(leaf_paths(params),
                               jax.tree.leaves(p_flags)) if f}
            | {p for p, f in zip(leaf_paths(state),
                                 jax.tree.leaves(s_flags)) if f})


def test_selection_weights_interleaves_bn_state(keypair):
    """The percent knob slices the FULL get_weights() list — BN moving
    statistics interleave with the weights (secure_fed_model.py:115-121:
    `self.weights[:num_enc]` over Keras get_weights()). int(14*0.5)=7 →
    b1's mean/var (STATE) are protected while c2's bias (a PARAM) is not.
    The same enumeration must drive PaillierClient.enc_model."""
    model = _bn_cnn()
    variables = model.init(jax.random.key(0))
    protected = _protected_paths(variables.params, variables.state, 0.5,
                                 model.layer_names)
    assert protected == {
        ("backbone", "c1", "kernel"), ("backbone", "c1", "bias"),
        ("backbone", "b1", "scale"), ("backbone", "b1", "bias"),
        ("backbone", "b1", "mean"), ("backbone", "b1", "var"),
        ("backbone", "c2", "kernel"),
    }
    # cross-check against the host-side Paillier path: enc_model encrypts
    # exactly the first 7 tensors of the same enumeration (object arrays),
    # in the same order and shapes
    pub, priv = keypair
    imgs, labels = synthetic.make_idc_like(8, size=10, seed=0)
    client = PaillierClient(model, rmsprop(1e-3), binary_cross_entropy,
                            imgs, labels, client_id=0, percent=0.5,
                            public_key=pub, private_key=priv)
    out = client.enc_model()
    assert len(out) == 14 and client._num_encrypted() == 7
    enc_shapes = [t.shape for t in out[:7]]
    assert all(t.dtype == object for t in out[:7])
    assert not any(t.dtype == object for t in out[7:])
    assert enc_shapes == [(3, 3, 3, 4), (4,), (4,), (4,), (4,), (4,),
                          (3, 3, 4, 4)]


def test_masked_selection_matches_paillier_enumeration_mobilenet():
    """VERDICT r2 #2: on a real BN zoo model the masked path's protected
    set must equal the PaillierClient enumeration's first int(L*percent)
    — params and moving stats interleaved, not params-only."""
    from idc_models_tpu.models.mobilenet import mobilenet_v2
    from idc_models_tpu.secure.masking import leaf_paths, ranked_indices

    model = mobilenet_v2(1)
    def init_shapes():
        v = model.init(jax.random.key(0))
        return dict(p=v.params, s=v.state)

    shapes = jax.eval_shape(init_shapes)
    params, state = shapes["p"], shapes["s"]
    percent = 0.25
    protected = _protected_paths(params, state, percent, model.layer_names)

    # PaillierClient._flat_weights enumeration: combined paths ranked by
    # model layer order; _num_encrypted = int((P+S) * percent)
    paths = leaf_paths(params) + leaf_paths(state)
    order = ranked_indices(paths, model.layer_names)
    n_enc = int(len(paths) * percent)
    assert protected == {paths[i] for i in order[:n_enc]}
    # the interleaving is real: the stem BN's moving stats are protected
    assert ("backbone", "bn_Conv1", "mean") in protected
    assert ("backbone", "bn_Conv1", "var") in protected
    # and a params-only selection would be a DIFFERENT set
    p_only = first_fraction_selection(params, percent, model.layer_names)
    p_only_set = {p for p, f in zip(leaf_paths(params),
                                    jax.tree.leaves(p_only)) if f}
    assert p_only_set != protected


def test_secure_round_bn_model_matches_plain_round(devices):
    """A masked round over a BN model (percent=0.5: protected set spans
    params AND moving stats) aggregates both to the plain unweighted
    mean, up to quantization error on the masked half."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = _bn_cnn()
    opt = rmsprop(1e-3)
    imgs, labels = _client_data()
    rng = jax.random.key(17)

    server_a = initialize_server(model, jax.random.key(0))
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=0.5,
        local_epochs=1, batch_size=16)
    sa, ma = secure_rnd(server_a, imgs, labels, rng)

    server_b = initialize_server(model, jax.random.key(0))
    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, mb = plain_rnd(server_b, imgs, labels,
                       np.ones((N_CLIENTS,), np.float32), rng)

    for a, b in zip(jax.tree.leaves(jax.device_get(sa.params)),
                    jax.tree.leaves(jax.device_get(sb.params))):
        np.testing.assert_allclose(a, b, atol=3e-6)
    # protected moving stats ride the int path at 1/256 prescale (range
    # for ImageNet-scale variances), so their resolution is 256 * 2^-sb
    for a, b in zip(jax.tree.leaves(jax.device_get(sa.model_state)),
                    jax.tree.leaves(jax.device_get(sb.model_state))):
        np.testing.assert_allclose(a, b, atol=1e-3)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-5)


def test_secure_round_bn_large_variance_not_clipped(devices):
    """ImageNet-scale BN moving variances (hundreds to thousands) exceed
    the +-64 weight clipping range; the protected-state prescale must
    carry them through the masked int path undamaged (the code-review r3
    finding: without it the server's BN state silently clips to 64)."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = _bn_cnn()
    opt = rmsprop(1e-3)
    imgs, labels = _client_data()
    rng = jax.random.key(23)

    def with_big_var(server):
        state = jax.tree.map(lambda x: x, server.model_state)
        state["backbone"]["b1"]["var"] = jnp.full_like(
            state["backbone"]["b1"]["var"], 3000.0)
        state["backbone"]["b1"]["mean"] = jnp.full_like(
            state["backbone"]["b1"]["mean"], -200.0)
        return server.replace(model_state=state)

    # percent=1.0: the b1 moving stats are protected (masked int path)
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=1.0,
        local_epochs=1, batch_size=16)
    sa, _ = secure_rnd(with_big_var(initialize_server(model,
                                                      jax.random.key(0))),
                       imgs, labels, rng)

    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, _ = plain_rnd(with_big_var(initialize_server(model,
                                                     jax.random.key(0))),
                      imgs, labels, np.ones((N_CLIENTS,), np.float32), rng)

    a = jax.device_get(sa.model_state)["backbone"]["b1"]
    b = jax.device_get(sb.model_state)["backbone"]["b1"]
    # aggregated var stays ~3000 (momentum 0.99 barely moves it) and must
    # match the plain mean to prescaled-quantization resolution
    assert float(np.min(a["var"])) > 2900.0
    np.testing.assert_allclose(a["var"], b["var"], rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(a["mean"], b["mean"], rtol=1e-5, atol=1e-2)


def test_pack_unpack_roundtrip():
    from idc_models_tpu.secure.masking import pack_leaves, unpack_leaves

    leaves = [jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
              jnp.asarray(2.5, jnp.float32),
              jnp.ones((4,), jnp.bfloat16)]
    flat, meta = pack_leaves(leaves)
    assert flat.shape == (11,) and flat.dtype == jnp.float32
    back = unpack_leaves(flat, meta)
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    # empty pack (percent=1.0 with empty state) round-trips too
    flat0, meta0 = pack_leaves([])
    assert flat0.shape == (0,) and unpack_leaves(flat0, meta0) == []


def test_secure_round_pallas_impl_bit_identical(devices):
    """threefry and pallas mask streams differ, but both cancel exactly
    under psum — the aggregated round results must be bit-identical."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=2)
    rng = jax.random.key(13)

    results = {}
    for impl in ("threefry", "pallas"):
        server = initialize_server(model, jax.random.key(0))
        rnd = make_secure_fedavg_round(
            model, opt, binary_cross_entropy, mesh, percent=0.5,
            local_epochs=1, batch_size=16, mask_impl=impl)
        s, m = rnd(server, imgs, labels, rng)
        results[impl] = (jax.device_get(s.params), float(m["loss"]))

    for a, b in zip(jax.tree.leaves(results["threefry"][0]),
                    jax.tree.leaves(results["pallas"][0])):
        np.testing.assert_array_equal(a, b)
    assert results["threefry"][1] == results["pallas"][1]


def test_secure_fedavg_loss_decreases(devices):
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=4)
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=0.5,
        local_epochs=2, batch_size=16)
    server = initialize_server(model, jax.random.key(0))
    key = jax.random.key(5)
    losses = []
    for _ in range(10):
        key, sub = jax.random.split(key)
        server, m = secure_rnd(server, imgs, labels, sub)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.95, losses


def test_paillier_clients_full_protocol(keypair):
    """The host-side parity protocol end-to-end with 3 clients on tiny
    shards: fit -> encrypt -> aggregate(ciphertext) -> decrypt -> update;
    the aggregate equals the plain mean of the clients' weights."""
    pub, priv = keypair
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = synthetic.make_idc_like(24, size=10, seed=9)
    clients = [
        PaillierClient(model, opt, binary_cross_entropy,
                       imgs[i::3], labels[i::3], i, percent=0.4,
                       public_key=pub, private_key=priv,
                       local_epochs=1, batch_size=8, seed=0)
        for i in range(3)
    ]
    packages = []
    for c in clients:
        pkg, _ = c.client_fit()
        packages.append(pkg)
    expected = [
        np.mean([np.asarray(x, np.float64)
                 for x in [jax.tree.leaves(c.params)[i] for c in clients]],
                axis=0)
        for i in range(len(jax.tree.leaves(clients[0].params)))
    ]
    agg = PaillierServer.aggregate(packages)
    for c in clients:
        c.client_update(agg)
    for c in clients:
        got = [np.asarray(x) for x in jax.tree.leaves(c.params)]
        for g, e in zip(got, expected):
            np.testing.assert_allclose(g, e, rtol=1e-5, atol=1e-7)
    m = clients[0].evaluate(imgs, labels, binary_cross_entropy)
    assert np.isfinite(m["loss"]) and 0 <= m["accuracy"] <= 1


def test_resolve_mask_impl_auto():
    """mask_impl="auto" picks the fused kernel exactly when (a) a TPU
    backend is live and (b) the protected buffer reaches the measured
    crossover (masking.MASK_PALLAS_MIN_ELEMS) — threefry everywhere
    else, including always off-TPU (interpret mode is unusable)."""
    from idc_models_tpu.models.vgg import vgg16
    from idc_models_tpu.secure import resolve_mask_impl

    big = vgg16(1)           # ~14.7M params >> 4.2M crossover
    small = small_cnn(10, 3, 1)
    assert resolve_mask_impl(big, 1.0, platform="tpu") == "pallas"
    assert resolve_mask_impl(big, 1.0, platform="axon") == "pallas"
    # a small protected slice of a big model stays under the crossover
    assert resolve_mask_impl(big, 0.05, platform="tpu") == "threefry"
    assert resolve_mask_impl(small, 1.0, platform="tpu") == "threefry"
    # off-TPU: always threefry, regardless of size
    assert resolve_mask_impl(big, 1.0, platform="cpu") == "threefry"
    # this suite runs on the CPU pod, so "auto" rounds build threefry
    assert resolve_mask_impl(big, 1.0) == "threefry"
