"""Secure aggregation: mask cancellation, Paillier round-trips, and the
secure FedAvg round (SURVEY.md §4: "masks cancel: psum of masked == psum
of plain; Paillier enc→agg→dec == plain mean")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import initialize_server, make_fedavg_round
from idc_models_tpu.models import small_cnn
from idc_models_tpu.secure import (
    dequantize, first_fraction_selection, make_secure_fedavg_round,
    pairwise_mask, quantize,
)
from idc_models_tpu.secure.fedavg import PaillierClient, PaillierServer
from idc_models_tpu.secure.paillier import generate_paillier_keypair
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N_CLIENTS = 8


def test_masks_cancel_exactly():
    """Sum over all clients of the pairwise masks is exactly zero."""
    key = jax.random.key(7)
    shape = (33, 5)
    total = jnp.zeros(shape, jnp.int32)
    for i in range(N_CLIENTS):
        total = total + pairwise_mask(key, jnp.int32(i), N_CLIENTS, shape)
    np.testing.assert_array_equal(np.asarray(total), 0)


def test_masked_psum_equals_plain_psum():
    """psum of masked quantized updates == psum of plain ones, bit-exact,
    while each individual masked contribution is (pseudo)random."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    key = jax.random.key(3)
    vals = np.random.default_rng(0).normal(size=(N_CLIENTS, 17)).astype(
        np.float32)

    def body(x):
        cid = collectives.axis_index(meshlib.CLIENT_AXIS)
        q = quantize(x[0])
        m = pairwise_mask(key, cid, N_CLIENTS, q.shape)
        masked_sum = collectives.psum(q + m, meshlib.CLIENT_AXIS)
        plain_sum = collectives.psum(q, meshlib.CLIENT_AXIS)
        return masked_sum, plain_sum, (q + m)[None]

    from jax.sharding import PartitionSpec as P
    f = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(meshlib.CLIENT_AXIS),
        out_specs=(P(), P(), P(meshlib.CLIENT_AXIS)), check_vma=False))
    masked_sum, plain_sum, contributions = f(vals)
    np.testing.assert_array_equal(np.asarray(masked_sum),
                                  np.asarray(plain_sum))
    # each device's masked contribution differs from its plain quantized
    # update. NOTE: this is a simulation-level property only — the round
    # key that derives the pairwise masks is held by the driver, so a
    # party with that key could regenerate the masks (masking.py
    # docstring; reference quirk Q9 keeps both Paillier keys global too).
    q_plain = np.asarray(quantize(jnp.asarray(vals)))
    assert not np.array_equal(np.asarray(contributions), q_plain)
    # and the dequantized mean matches the true mean to quantization error
    mean = np.asarray(dequantize(masked_sum, count=N_CLIENTS))
    np.testing.assert_allclose(mean, vals.mean(0), atol=2e-6)


def test_quantize_roundtrip():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(100,)) * 5)
    back = dequantize(quantize(x))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-6)


def test_dequantize_keeps_resolution_for_large_sums():
    """Sums past 2^24 (reachable with clip 64, scale 20, 8 clients) must
    not lose low bits: the split evaluation matches a float64 reference
    exactly for power-of-two counts (one rounding, at the result)."""
    s = 20
    q_np = np.asarray([2**24 + 1, -(2**24 + 1), 2**29 + 3, (1 << 31) - 1,
                       -(1 << 31), 12345, 0], np.int64)
    got = np.asarray(dequantize(jnp.asarray(q_np, jnp.int32), s, count=8))
    want = (q_np.astype(np.float64) / 2**s / 8).astype(np.float32)
    np.testing.assert_array_equal(got, want)
    # non-power-of-two count: one extra rounding, still ~ulp accurate
    got3 = np.asarray(dequantize(jnp.asarray(q_np, jnp.int32), s, count=3))
    np.testing.assert_allclose(
        got3, (q_np.astype(np.float64) / 2**s / 3).astype(np.float32),
        rtol=1e-7)


def test_paillier_exponent_gap_overflow_raises(keypair):
    """Aligning exponents across a huge magnitude gap would wrap the
    mantissa mod n and decrypt to garbage; it must raise instead."""
    pub, _ = keypair
    big = pub.encrypt(1e100)
    tiny = pub.encrypt(1e-100)
    with pytest.raises(ValueError, match="overflow"):
        _ = big + tiny
    # scalar multiplication grows the tracked mantissa bound (106 bits
    # here); a fixed 53-bit-mantissa assumption would wave this through
    # and the sum would wrap mod n and decrypt to garbage
    a = pub.encrypt(1e100) * 0.3
    b = pub.encrypt(1e-30) * 0.7
    with pytest.raises(ValueError, match="overflow"):
        _ = a + b
    # ordinary same-scale arithmetic is untouched by the guard
    _ = pub.encrypt(1e10) + pub.encrypt(1e-10) * 0.5


def test_quantize_clips_instead_of_wrapping():
    from idc_models_tpu.secure import choose_scale_bits

    big = jnp.asarray([1e9, -1e9, 10.0])
    q = quantize(big, 20, clip_abs=64.0)
    back = dequantize(q, 20)
    np.testing.assert_allclose(np.asarray(back), [64.0, -64.0, 10.0],
                               atol=1e-5)
    # headroom budget: sum of n fully saturated values must fit int32
    # STRICTLY (2^31 exactly would wrap to INT32_MIN)
    for n in (2, 8, 32, 1024):
        bits = choose_scale_bits(n, 64.0)
        assert (2.0 ** bits) * 64.0 * n <= 2 ** 31 - 1
    assert choose_scale_bits(8, 64.0) <= 21


def test_first_fraction_selection():
    tree = {"a": 1, "b": {"c": 2, "d": 3}, "e": 4}
    sel = first_fraction_selection(tree, 0.5)
    flags = jax.tree.leaves(sel)
    assert flags == [True, True, False, False]  # int(4*0.5)=2
    assert jax.tree.leaves(first_fraction_selection(tree, 0.0)) == [False] * 4
    assert jax.tree.leaves(first_fraction_selection(tree, 1.0)) == [True] * 4


def test_first_fraction_selection_layer_order():
    """With a model's layer_names, "first N tensors" follows Keras
    get_weights() order (layer creation order, kernel before bias), not
    jax's alphabetical flatten (secure_fed_model.py:115-121 parity)."""
    model = small_cnn(10, 3, 1)
    params = model.init(jax.random.key(0)).params
    # small_cnn layer order: conv1 -> fc1 -> head; get_weights() order is
    # conv1/kernel, conv1/bias, fc1/kernel, fc1/bias, head/kernel, head/bias.
    sel = first_fraction_selection(params, 0.5, model.layer_names)
    assert sel == {
        "conv1": {"kernel": True, "bias": True},
        "fc1": {"kernel": True, "bias": False},
        "head": {"kernel": False, "bias": False},
    }
    # alphabetical order would instead have protected conv1/bias,
    # conv1/kernel, fc1/bias — a different set
    sel_flat = first_fraction_selection(params, 0.5)
    assert sel_flat["fc1"] == {"kernel": False, "bias": True}


def test_first_fraction_selection_nested_classifier():
    """classifier() models rank backbone layers in creation order via
    dotted layer_names (not alphabetically), head last."""
    from idc_models_tpu.models import core

    backbone = core.sequential(
        [core.conv2d(3, 4, 3, name="z_first"),   # alphabetically LAST
         core.conv2d(4, 4, 3, name="a_second")],  # alphabetically FIRST
        name="bb")
    model = core.classifier(backbone, 4, 1)
    assert model.layer_names == ("backbone.z_first", "backbone.a_second",
                                 "head")
    params = model.init(jax.random.key(0)).params
    # first 3 of 6 tensors: z_first kernel+bias, a_second kernel
    sel = first_fraction_selection(params, 0.5, model.layer_names)
    assert sel == {
        "backbone": {"z_first": {"kernel": True, "bias": True},
                     "a_second": {"kernel": True, "bias": False}},
        "head": {"kernel": False, "bias": False},
    }


@pytest.fixture(scope="module")
def keypair():
    return generate_paillier_keypair(n_length=512)


class TestPaillier:
    def test_roundtrip(self, keypair):
        pub, priv = keypair
        for v in [0.0, 1.5, -2.75, 1e-8, -1e8, 123456.789]:
            assert priv.decrypt(pub.encrypt(v)) == pytest.approx(v, rel=1e-12)

    def test_homomorphic_add(self, keypair):
        pub, priv = keypair
        a, b = 3.25, -1.125
        s = pub.encrypt(a) + pub.encrypt(b)
        assert priv.decrypt(s) == pytest.approx(a + b, rel=1e-12)

    def test_scalar_mul_div(self, keypair):
        pub, priv = keypair
        c = pub.encrypt(7.5) * 0.125
        assert priv.decrypt(c) == pytest.approx(0.9375, rel=1e-9)
        d = pub.encrypt(10.0) / 8
        assert priv.decrypt(d) == pytest.approx(1.25, rel=1e-9)

    def test_ciphertext_mean_equals_plain_mean(self, keypair):
        pub, priv = keypair
        vals = [0.5, -1.5, 2.25, 3.0]
        enc = [pub.encrypt(v) for v in vals]
        acc = enc[0]
        for e in enc[1:]:
            acc = acc + e
        mean = acc / len(vals)
        assert priv.decrypt(mean) == pytest.approx(
            sum(vals) / len(vals), rel=1e-9)


def _client_data(n_per_client=32, seed=0):
    imgs, labels = synthetic.make_idc_like(n_per_client * N_CLIENTS, size=10,
                                           seed=seed)
    return partition_clients(ArrayDataset(imgs, labels), N_CLIENTS, iid=True,
                             seed=seed)


def test_secure_round_matches_plain_round(devices):
    """percent=1.0 secure round == plain unweighted FedAvg round up to
    quantization error (same rng, same local training)."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data()
    rng = jax.random.key(11)

    server_a = initialize_server(model, jax.random.key(0))
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=1.0,
        local_epochs=1, batch_size=16)
    sa, ma = secure_rnd(server_a, imgs, labels, rng)

    server_b = initialize_server(model, jax.random.key(0))
    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, mb = plain_rnd(server_b, imgs, labels,
                       np.ones((N_CLIENTS,), np.float32), rng)

    for a, b in zip(jax.tree.leaves(jax.device_get(sa.params)),
                    jax.tree.leaves(jax.device_get(sb.params))):
        np.testing.assert_allclose(a, b, atol=3e-6)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-5)


def test_secure_round_recovers_diverged_client(devices):
    """Failure recovery on the masked path, where a client cannot simply
    be dropped (its pairwise masks would stay uncancelled): the diverged
    client's update is replaced with the incoming global weights before
    masking. Expected aggregate = (7 healthy updates + old weights) / 8;
    the healthy updates come from the plain round with the dead client
    auto-dropped (identical rng derivation, proven by
    test_secure_round_matches_plain_round)."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=17)
    poisoned = np.array(imgs)
    poisoned[3] = np.nan
    rng = jax.random.key(23)

    server = initialize_server(model, jax.random.key(0))
    old_params = jax.device_get(server.params)
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=1.0,
        local_epochs=1, batch_size=16)
    sa, ma = secure_rnd(server, poisoned, labels, rng)
    assert int(ma["clients_recovered"]) == 1
    assert np.isfinite(float(ma["loss"]))
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(sa.params)))

    # healthy-only mean via the plain round's failure detection
    plain_rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                  local_epochs=1, batch_size=16)
    sb, mb = plain_rnd(initialize_server(model, jax.random.key(0)),
                       poisoned, labels, np.ones((N_CLIENTS,), np.float32),
                       rng)
    for a, healthy_mean, old in zip(
            jax.tree.leaves(jax.device_get(sa.params)),
            jax.tree.leaves(jax.device_get(sb.params)),
            jax.tree.leaves(old_params)):
        want = (healthy_mean * (N_CLIENTS - 1) + old) / N_CLIENTS
        np.testing.assert_allclose(a, want, atol=5e-6)
    # metrics average only the clients that actually trained
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-5)

    # recovery can be disabled: the diverged client then poisons the
    # masked aggregate (why the default is on)
    rnd_off = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=0.5,
        local_epochs=1, batch_size=16, recover_nonfinite=False)
    s_off, _ = rnd_off(initialize_server(model, jax.random.key(0)),
                       poisoned, labels, rng)
    assert not all(np.all(np.isfinite(l))
                   for l in jax.tree.leaves(jax.device_get(s_off.params)))


def test_secure_round_layout_invariant(devices):
    """k clients per device: the same 8 clients on an 8-device mesh
    (k=1), a 4-device mesh (k=2), and a 1-device mesh (k=8) produce the
    same aggregate — the protected int32 path bit-for-bit (mod-2^32
    addition is layout-independent), the f32 path to fp tolerance."""
    model = small_cnn(10, 3, 1)
    ci, cl = _client_data(seed=13)
    rng = jax.random.key(21)

    def run(n_dev, impl="threefry"):
        mesh = meshlib.client_mesh(n_dev)
        server = initialize_server(model, jax.random.key(0))
        rnd = make_secure_fedavg_round(
            model, rmsprop(1e-3), binary_cross_entropy, mesh, percent=0.5,
            local_epochs=1, batch_size=16, mask_impl=impl)
        server, m = rnd(server, ci, cl, rng)
        return jax.device_get(server.params), float(m["loss"])

    p8, l8 = run(8)
    p4, l4 = run(4)
    p1, l1 = run(1)
    # the pallas impl's masks differ but cancel identically, so even a
    # k=2 pallas layout must land on the same aggregate (exercises the
    # per-client kernel loop with k > 1)
    p4p, l4p = run(4, impl="pallas")
    for ref in (p4, p1, p4p):
        for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(ref)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose([l4, l1, l4p], l8, rtol=1e-5)
    # non-divisible layout is refused (no padding for unweighted means)
    mesh3 = meshlib.client_mesh(3)
    rnd3 = make_secure_fedavg_round(
        model, rmsprop(1e-3), binary_cross_entropy, mesh3, percent=0.5,
        local_epochs=1, batch_size=16)
    with pytest.raises(ValueError, match="divides"):
        rnd3(initialize_server(model, jax.random.key(0)), ci, cl, rng)


def test_mobilenet_selection_follows_keras_order():
    """Zoo backbones carry layer_names, so percent-selection follows the
    Keras get_weights() enumeration (VERDICT r1 weak #4): creation order
    with kernel -> scale -> bias within a layer, head last."""
    from idc_models_tpu.models.mobilenet import mobilenet_v2
    from idc_models_tpu.secure.masking import leaf_paths, ranked_indices

    model = mobilenet_v2(1)
    assert model.layer_names[0] == "backbone.Conv1"
    assert model.layer_names[-1] == "head"
    shapes = jax.eval_shape(lambda: dict(p=model.init(jax.random.key(0))
                                         .params))["p"]
    paths = leaf_paths(shapes)
    ordered = [paths[i] for i in ranked_indices(paths, model.layer_names)]
    assert ordered[0] == ("backbone", "Conv1", "kernel")
    assert ordered[1] == ("backbone", "bn_Conv1", "scale")
    assert ordered[2] == ("backbone", "bn_Conv1", "bias")
    assert ordered[3] == ("backbone", "expanded_conv_depthwise", "kernel")
    assert ordered[-2:] == [("head", "kernel"), ("head", "bias")]
    # densenet too: first parameterized layer is conv1_conv
    from idc_models_tpu.models.densenet import densenet201

    dn = densenet201(10)
    assert dn.layer_names[0] == "backbone.conv1_conv"
    assert dn.layer_names[-1] == "head"


def test_pack_unpack_roundtrip():
    from idc_models_tpu.secure.masking import pack_leaves, unpack_leaves

    leaves = [jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
              jnp.asarray(2.5, jnp.float32),
              jnp.ones((4,), jnp.bfloat16)]
    flat, meta = pack_leaves(leaves)
    assert flat.shape == (11,) and flat.dtype == jnp.float32
    back = unpack_leaves(flat, meta)
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    # empty pack (percent=1.0 with empty state) round-trips too
    flat0, meta0 = pack_leaves([])
    assert flat0.shape == (0,) and unpack_leaves(flat0, meta0) == []


def test_secure_round_pallas_impl_bit_identical(devices):
    """threefry and pallas mask streams differ, but both cancel exactly
    under psum — the aggregated round results must be bit-identical."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=2)
    rng = jax.random.key(13)

    results = {}
    for impl in ("threefry", "pallas"):
        server = initialize_server(model, jax.random.key(0))
        rnd = make_secure_fedavg_round(
            model, opt, binary_cross_entropy, mesh, percent=0.5,
            local_epochs=1, batch_size=16, mask_impl=impl)
        s, m = rnd(server, imgs, labels, rng)
        results[impl] = (jax.device_get(s.params), float(m["loss"]))

    for a, b in zip(jax.tree.leaves(results["threefry"][0]),
                    jax.tree.leaves(results["pallas"][0])):
        np.testing.assert_array_equal(a, b)
    assert results["threefry"][1] == results["pallas"][1]


def test_secure_fedavg_loss_decreases(devices):
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=4)
    secure_rnd = make_secure_fedavg_round(
        model, opt, binary_cross_entropy, mesh, percent=0.5,
        local_epochs=2, batch_size=16)
    server = initialize_server(model, jax.random.key(0))
    key = jax.random.key(5)
    losses = []
    for _ in range(10):
        key, sub = jax.random.split(key)
        server, m = secure_rnd(server, imgs, labels, sub)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.95, losses


def test_paillier_clients_full_protocol(keypair):
    """The host-side parity protocol end-to-end with 3 clients on tiny
    shards: fit -> encrypt -> aggregate(ciphertext) -> decrypt -> update;
    the aggregate equals the plain mean of the clients' weights."""
    pub, priv = keypair
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = synthetic.make_idc_like(24, size=10, seed=9)
    clients = [
        PaillierClient(model, opt, binary_cross_entropy,
                       imgs[i::3], labels[i::3], i, percent=0.4,
                       public_key=pub, private_key=priv,
                       local_epochs=1, batch_size=8, seed=0)
        for i in range(3)
    ]
    packages = []
    for c in clients:
        pkg, _ = c.client_fit()
        packages.append(pkg)
    expected = [
        np.mean([np.asarray(x, np.float64)
                 for x in [jax.tree.leaves(c.params)[i] for c in clients]],
                axis=0)
        for i in range(len(jax.tree.leaves(clients[0].params)))
    ]
    agg = PaillierServer.aggregate(packages)
    for c in clients:
        c.client_update(agg)
    for c in clients:
        got = [np.asarray(x) for x in jax.tree.leaves(c.params)]
        for g, e in zip(got, expected):
            np.testing.assert_allclose(g, e, rtol=1e-5, atol=1e-7)
    m = clients[0].evaluate(imgs, labels, binary_cross_entropy)
    assert np.isfinite(m["loss"]) and 0 <= m["accuracy"] <= 1
