"""The continuous-batching serving engine (serve/) against its two hard
contracts:

1. TOKEN PARITY — with identical prompts/seeds, the engine's per-request
   outputs are bit-identical to serial `Generator` calls (greedy and
   seeded top-k), including across a slot-recycle boundary (a request
   admitted into the slot another vacated mid-run). The engine shares
   the serial path's prefill program, per-token forward, fold algebra,
   and sampling rule — this gates that the sharing actually holds.
2. ZERO RECOMPILATION — after warmup, admitting requests of varying
   prompt lengths and budgets into a running engine triggers no new XLA
   compilations (jit cache-size counters).

Plus the scheduling semantics: FIFO admission with backpressure,
deadlines (queued drop + running cancel), EOS/budget recycling, masked
no-op appends for dead slots, and the serving metrics rollup.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import (
    LMServer, Request, SlotEngine, load_trace, poisson_trace, save_trace,
)

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw(mesh=None):
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)


def _serial_tokens(gen, prompt, steps, *, rng=None):
    """The serial reference: prefill + one fused decode, generated
    tokens only."""
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps, rng=rng)
    return toks.tolist()[0]


def test_token_parity_and_no_recompile_greedy(devices, params):
    """The acceptance pair in one run: 8 greedy requests of VARYING
    prompt lengths and budgets through 3 slots — so slots recycle
    mid-run — must (a) emit bit-identical tokens to serial Generator
    calls and (b) grow no jit cache after the warmup + first admission
    wave."""
    server = LMServer(params, n_slots=3, window=4, **_kw())
    rng = np.random.default_rng(5)
    reqs = [Request(id=f"r{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 3 + 2 * i)),
                    max_new_tokens=4 + (i % 5) * 2)
            for i in range(8)]
    # first wave: two requests, then freeze the compile counters
    server.run([(0.0, r) for r in reqs[:2]])
    sizes = server.engine.cache_sizes()
    # second wave: six NEW lengths/budgets into the running engine
    server.run([(0.0, r) for r in reqs[2:]])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)

    gen = Generator(params, **_kw())
    for r in reqs:
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert got.tokens == want, (r.id, got.tokens, want)


def test_token_parity_across_slot_recycle(devices, params):
    """Request C fills the slot request A vacated mid-run (B still
    decoding) — C's output must equal its serial generation exactly."""
    eng = SlotEngine(params, n_slots=2, **_kw())
    eng.warmup(4)
    rng = np.random.default_rng(7)
    pa = rng.integers(0, VOCAB, 9)
    pb = rng.integers(0, VOCAB, 5)
    pc = rng.integers(0, VOCAB, 13)
    eng.admit(0, pa, 5)
    eng.admit(1, pb, 17)
    got = {0: [], 1: []}
    got_c, c_admitted = [], False
    for _ in range(16):
        for s, t in eng.step_window(4).items():
            (got_c if (s == 0 and c_admitted) else got[s]).extend(t)
        if eng.finished(0):
            eng.release(0)
            if not c_admitted:
                eng.admit(0, pc, 7)
                c_admitted = True
        if eng.finished(1):
            eng.release(1)
        if c_admitted and not eng._occupied.any():
            break
    gen = Generator(params, **_kw())
    assert got[0] == _serial_tokens(gen, tuple(pa), 5)
    assert got[1] == _serial_tokens(gen, tuple(pb), 17)
    assert got_c == _serial_tokens(gen, tuple(pc), 7)


def test_token_parity_sampled_on_ring(devices, params):
    """Seeded top-k sampling through the RING-SHARDED engine (caches
    sharded over a seq=4 mesh): per-request streams must match serial
    decode with the same per-request key, bit for bit."""
    mesh = meshlib.seq_mesh(4)
    server = LMServer(params, n_slots=2, window=4, temperature=1.3,
                      top_k=4, **_kw(mesh))
    rng = np.random.default_rng(9)
    reqs = [Request(id=f"s{i}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + 3 * i)),
                    max_new_tokens=6, seed=100 + i)
            for i in range(4)]
    server.run([(0.0, r) for r in reqs])
    gen = Generator(params, temperature=1.3, top_k=4, **_kw(mesh))
    for r in reqs:
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens,
                              rng=jax.random.key(r.seed))
        assert server.poll(r.id).tokens == want, r.id


def test_eos_stops_and_recycles(devices, params):
    """A request whose stream hits its stop token finishes early
    (finish_reason 'eos', EOS included), frees the slot for the queue,
    and matches the serial stream truncated at the first EOS."""
    gen = Generator(params, **_kw())
    prompt = (1, 2, 3)
    stream = _serial_tokens(gen, prompt, 12)
    eos = stream[3]                      # guaranteed to appear
    cut = stream[:stream.index(eos) + 1]
    server = LMServer(params, n_slots=1, window=4, eos_id=eos, **_kw())
    out = server.run([(0.0, Request(id="a", prompt=prompt,
                                    max_new_tokens=12)),
                      (0.0, Request(id="b", prompt=(4, 5),
                                    max_new_tokens=3, eos_id=-1))])
    a = server.poll("a")
    assert a.finish_reason == "eos" and a.tokens == cut
    b = server.poll("b")                 # eos_id=-1 opts out
    assert b.finish_reason == "budget" and len(b.tokens) == 3
    assert len(out) == 2


def test_backpressure_and_rejection(devices, params):
    """Bounded admission queue: submits beyond max_queue_depth return
    False; run(on_full='reject') records rejected Results; 'block'
    (default) serves everything in FIFO order."""
    server = LMServer(params, n_slots=1, window=4, max_queue_depth=2,
                      **_kw())
    reqs = [Request(id=f"q{i}", prompt=(i + 1,), max_new_tokens=2)
            for i in range(4)]
    assert server.submit(reqs[0])
    assert server.submit(reqs[1])
    assert not server.submit(reqs[2])    # depth 2 -> backpressure
    server.drain()
    assert server.poll("q0").status == "ok"
    rs = server.run([(0.0, Request(id="q9", prompt=(1,), max_new_tokens=2)),
                     (0.0, Request(id="q10", prompt=(2,), max_new_tokens=2)),
                     (0.0, Request(id="q11", prompt=(3,), max_new_tokens=2)),
                     (0.0, Request(id="q12", prompt=(4,), max_new_tokens=2))],
                    on_full="reject")
    statuses = {r.id: r.status for r in rs}
    assert statuses["q11"] == "rejected" or statuses["q12"] == "rejected"
    # blocking mode serves every request eventually — and a request that
    # merely WAITED for queue room must not count as rejected
    server2 = LMServer(params, n_slots=1, window=4, max_queue_depth=2,
                       **_kw())
    rs2 = server2.run([(0.0, Request(id=f"b{i}", prompt=(i + 1,),
                                     max_new_tokens=2))
                       for i in range(5)])
    assert sum(r.status == "ok" for r in rs2) == 5
    assert server2.summary()["serve_rejected"] == 0
    # duplicate ids are refused while the original is still in flight
    server2.submit(Request(id="dup", prompt=(1,), max_new_tokens=2))
    with pytest.raises(ValueError, match="already used"):
        server2.submit(Request(id="dup", prompt=(2,), max_new_tokens=2))
    server2.drain()
    with pytest.raises(ValueError, match="already used"):
        server2.submit(Request(id="dup", prompt=(2,), max_new_tokens=2))


def test_deadlines_queued_and_running(devices, params):
    """Deadlines on a FAKE clock: a queued request past its deadline
    times out without occupying a slot; a running request is cancelled
    mid-generation with its partial tokens returned."""
    now = [0.0]

    def clock():
        return now[0]

    server = LMServer(params, n_slots=1, window=4, clock=clock, **_kw())
    # "slow" occupies the slot; "late" waits in the queue past its
    # deadline; "slow" itself dies mid-run at t=1
    server.submit(Request(id="slow", prompt=(1, 2), max_new_tokens=24,
                          deadline_s=1.0))
    server.submit(Request(id="late", prompt=(3,), max_new_tokens=4,
                          deadline_s=0.5))
    server.step()                        # admits "slow", first window
    now[0] = 0.6
    server.step()                        # expires "late" in the queue
    late = server.poll("late")
    assert late.status == "timeout" and late.finish_reason == "deadline"
    assert late.tokens == []
    now[0] = 1.1
    server.step()
    server.drain()
    slow = server.poll("slow")
    assert slow.status == "timeout" and slow.finish_reason == "deadline"
    assert 0 < len(slow.tokens) < 24     # partial output survives
    # the vacated slot serves the next request normally
    server.submit(Request(id="next", prompt=(4,), max_new_tokens=3))
    server.drain()
    assert server.poll("next").status == "ok"
    # both deadline paths count in the summary's timeout field
    assert server.summary()["serve_timed_out"] == 2


def test_dead_slot_cache_untouched(devices, params):
    """The masked append: windows decoded while a slot is dead leave its
    cache rows bit-untouched (the recycled request's correctness rests
    on this, and on insert overwriting the full row)."""
    eng = SlotEngine(params, n_slots=2, **_kw())
    eng.warmup(4)
    eng.admit(0, (1, 2, 3), 4)
    eng.admit(1, (4, 5), 20)
    while not eng.finished(0):
        eng.step_window(4)
    eng.release(0)
    before = [np.asarray(kc)[0].copy() for kc, _ in eng._caches]
    eng.step_window(4)                   # slot 0 dead, slot 1 decoding
    after = [np.asarray(kc)[0] for kc, _ in eng._caches]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)


def test_admit_rejections(devices, params):
    eng = SlotEngine(params, n_slots=1, **_kw())
    with pytest.raises(ValueError, match="exceeds t_max"):
        eng.admit(0, list(range(SEQ - 2)), 3)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.admit(0, (1, 2), 0)
    with pytest.raises(ValueError, match="non-empty"):
        eng.admit(0, np.zeros((1, 0), np.int32), 2)
    eng.admit(0, (1, 2), 2)
    with pytest.raises(ValueError, match="occupied"):
        eng.admit(0, (1, 2), 2)
    with pytest.raises(ValueError, match="seq-only"):
        SlotEngine(params, n_slots=1, **_kw(meshlib.data_seq_mesh(2, 2)))
    server = LMServer(params, n_slots=1, temperature=1.0, **_kw())
    with pytest.raises(ValueError, match="rng"):
        server.submit(Request(id="x", prompt=(1,), max_new_tokens=2))


def test_metrics_summary_and_jsonl(devices, params, tmp_path):
    """The serving metrics roll up into the bench-record fields and
    stream through JsonlLogger in the standard record shape."""
    import json

    from idc_models_tpu.observe import JsonlLogger

    log = tmp_path / "serve.jsonl"
    with JsonlLogger(log) as logger:
        server = LMServer(params, n_slots=2, window=4, logger=logger,
                          **_kw())
        server.run([(0.0, Request(id=f"m{i}", prompt=(1, 2, 3),
                                  max_new_tokens=5))
                    for i in range(3)])
        s = server.summary()
    assert s["serve_requests"] == 3 and s["serve_tokens"] == 15
    assert s["serve_tokens_per_sec"] > 0
    assert s["serve_ttft_ms_p50"] > 0
    assert s["serve_ttft_ms_p95"] >= s["serve_ttft_ms_p50"]
    assert 0 < s["serve_slot_occupancy"] <= 1
    recs = [json.loads(line) for line in
            log.read_text().splitlines()]
    events = {r["event"] for r in recs}
    assert {"serve_submit", "serve_first_token",
            "serve_finish"} <= events
    assert all("ts" in r for r in recs)


def test_trace_roundtrip_and_poisson(devices, tmp_path):
    trace = poisson_trace(6, rate_per_s=100.0, vocab=VOCAB, t_max=SEQ,
                          seed=3, eos_id=2, deadline_s=5.0, sampled=True)
    assert len(trace) == 6
    ts = [t for t, _ in trace]
    assert ts == sorted(ts) and all(t > 0 for t in ts)
    for _, r in trace:
        assert len(r.prompt) + r.max_new_tokens <= SEQ
        assert r.seed is not None
    p = save_trace(tmp_path / "t.jsonl", trace)
    assert load_trace(p) == trace


def test_trace_generation_is_byte_deterministic(devices, tmp_path):
    """ISSUE 12 satellite: same seed => byte-identical trace FILE. The
    cluster bench replays one trace against 1 vs 2 replica fleets; the
    comparison is meaningless if trace generation drifts between the
    passes, so determinism is gated at the byte level — generation,
    serialization, and the save->load->save fixpoint."""
    kw = dict(rate_per_s=75.0, vocab=VOCAB, t_max=SEQ, eos_id=2,
              deadline_s=5.0, sampled=True)
    a = poisson_trace(12, seed=42, **kw)
    b = poisson_trace(12, seed=42, **kw)
    assert a == b                       # full structural equality,
    #                                     Request fields included
    pa = save_trace(tmp_path / "a.jsonl", a)
    pb = save_trace(tmp_path / "b.jsonl", b)
    bytes_a = (tmp_path / "a.jsonl").read_bytes()
    assert bytes_a == (tmp_path / "b.jsonl").read_bytes()
    del pa, pb
    # a DIFFERENT seed must actually move the stream (the determinism
    # above is not the degenerate constant-output kind)
    c = poisson_trace(12, seed=43, **kw)
    assert c != a
    # save -> load -> save is a fixpoint: replaying from the file is
    # the same trace, byte for byte
    reloaded = load_trace(tmp_path / "a.jsonl")
    assert reloaded == a
    save_trace(tmp_path / "a2.jsonl", reloaded)
    assert (tmp_path / "a2.jsonl").read_bytes() == bytes_a


def test_chunked_prefill_token_parity_and_no_recompile(devices, params):
    """Chunked admission (prefill_chunk=8) at every boundary length —
    1, chunk-1, chunk, chunk+1 — emits tokens bit-identical to the
    serial MONOLITHIC Generator, and after the first wave admits of
    every further length compile nothing (the chunk program is one
    executable for all prompt lengths, including the ragged tail)."""
    server = LMServer(params, n_slots=2, window=4, prefill_chunk=8,
                      **_kw())
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(11)
    lens = [1, 7, 8, 9, 17]
    reqs = [Request(id=f"c{p}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, p)),
                    max_new_tokens=5)
            for p in lens]
    server.run([(0.0, reqs[0])])
    sizes = server.engine.cache_sizes()
    assert "prefill_chunk" in sizes
    server.run([(0.0, r) for r in reqs[1:]])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    for r in reqs:
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert server.poll(r.id).tokens == want, r.id
    # NOTE: sizes["prefill"] is not asserted 0 — the monolithic program
    # cache is process-wide per config and other tests share it; the
    # stability assertion above is the admission-path contract


def test_chunked_prefill_sampled_parity_with_prefix_hits(devices, params):
    """Seeded top-k sampling through CHUNKED admission WITH prefix-cache
    hits: per-request streams must still match the serial Generator with
    the same key, bit for bit — the request's rng stream is independent
    of how its prompt was prefilled (and on a 1-device serving mesh the
    chunk path's prefill state is bit-identical to the monolithic
    one)."""
    sys_p = tuple(int(x) for x in
                  np.random.default_rng(21).integers(0, VOCAB, 8))
    server = LMServer(params, n_slots=2, window=4, temperature=1.3,
                      top_k=4, prefill_chunk=8, prefix_cache_mb=64.0,
                      **_kw())
    reqs = [Request(id=f"t{i}", prompt=sys_p + (i,), max_new_tokens=6,
                    seed=300 + i)
            for i in range(4)]
    server.run([(0.0, r) for r in reqs])
    assert server.summary()["serve_prefix_hits"] >= 3
    gen = Generator(params, temperature=1.3, top_k=4, **_kw())
    for r in reqs:
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens,
                              rng=jax.random.key(r.seed))
        assert server.poll(r.id).tokens == want, r.id


def test_chunked_prefill_full_cache_prompt(devices, params):
    """Prompt length == t_max: the chunk path fills the entire cache
    and its final logits/caches match the monolithic prefill (argmax-
    equal logits, fp-close caches) — the upper boundary the chunk grid
    must tile exactly."""
    gen = Generator(params, **_kw())
    genc = Generator(params, prefill_chunk=8, **_kw())
    prompt = jnp.asarray(
        [np.random.default_rng(3).integers(0, VOCAB, SEQ)], jnp.int32)
    l0, c0 = gen.prefill(prompt)
    l1, c1 = genc.prefill(prompt)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                               rtol=2e-5, atol=2e-5)
    assert int(jnp.argmax(l0)) == int(jnp.argmax(l1))
    for (k0, v0), (k1, v1) in zip(c0, c1):
        np.testing.assert_allclose(np.asarray(k0), np.asarray(k1),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=2e-5, atol=2e-5)


def test_chunked_prefill_interleaves_with_decode(devices, params):
    """The point of chunking: while a long prompt is being prefilled
    chunk by chunk, an already-running request KEEPS emitting tokens
    every window — and the chunked request's own output still matches
    its serial generation bit-for-bit."""
    eng = SlotEngine(params, n_slots=2, prefill_chunk=4, **_kw())
    eng.warmup(4)
    rng = np.random.default_rng(13)
    pa = rng.integers(0, VOCAB, 3)
    pb = rng.integers(0, VOCAB, 17)          # 5 chunks of 4
    eng.admit(0, pa, 16)                     # decoding from the start
    eng.start_prefill(1, pb, 6)
    assert 1 not in eng.free_slots()         # reserved while chunking
    got_a, got_b, windows_during_prefill = [], [], 0
    done = False
    while not done:
        done = eng.prefill_step(1)
        out = eng.step_window(2)
        if not done:
            windows_during_prefill += 1
            assert out.get(0), "running slot stalled behind a prefill"
        got_a.extend(out.get(0, []))
        got_b.extend(out.get(1, []))
    while eng._occupied.any():
        for s, t in eng.step_window(2).items():
            (got_a if s == 0 else got_b).extend(t)
        for s in (0, 1):
            if eng.finished(s):
                eng.release(s)
    assert windows_during_prefill >= 4       # decode ran between chunks
    gen = Generator(params, **_kw())
    assert got_a == _serial_tokens(gen, tuple(pa), 16)
    assert got_b == _serial_tokens(gen, tuple(pb), 6)


def test_chunked_deadline_cancels_prefilling_request(devices, params):
    """A deadline that lands while a request is still CHUNKING its
    prompt cancels the prefill: the reserved slot frees immediately, no
    tokens are attributed, and the queue keeps moving."""
    now = [0.0]
    server = LMServer(params, n_slots=1, window=4, prefill_chunk=4,
                      clock=lambda: now[0], **_kw())
    # prompt of 5 chunks, one chunk per tick: deadline hits mid-chunking
    server.submit(Request(id="long", prompt=tuple(range(1, 18)),
                          max_new_tokens=4, deadline_s=1.0))
    server.step()                            # start + first chunk
    now[0] = 1.5
    server.step()                            # deadline: cancel_prefill
    r = server.poll("long")
    assert r is not None and r.status == "timeout"
    assert r.tokens == []
    server.submit(Request(id="next", prompt=(1, 2), max_new_tokens=3))
    server.drain()
    assert server.poll("next").status == "ok"


def test_int8_kv_capacity_and_bounded_drift(devices, params):
    """int8 KV: ring-cache bytes per slot drop >= 1.5x vs the same
    engine at bf16 (the capacity headroom the quantization buys), and
    the quantized engine's greedy decode still tracks the serial bf16
    path exactly on this model (drift is bounded well inside the
    greedy argmax margin at these scales; docs/LONG_CONTEXT.md owns the
    caveat for when it is not)."""
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, mesh=None, cache_dtype=jnp.bfloat16)
    eng16 = SlotEngine(params, n_slots=2, **kw)
    eng8 = SlotEngine(params, n_slots=2, kv_dtype="int8", **kw)
    ratio = eng16.kv_bytes_per_slot() / eng8.kv_bytes_per_slot()
    assert ratio >= 1.5, ratio
    server = LMServer(params, n_slots=2, window=4, kv_dtype="int8",
                      **_kw())
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(17)
    reqs = [Request(id=f"i{k}",
                    prompt=tuple(int(x) for x in
                                 rng.integers(0, VOCAB, 4 + 3 * k)),
                    max_new_tokens=6)
            for k in range(3)]
    server.run([(0.0, r) for r in reqs])
    for r in reqs:
        got = server.poll(r.id)
        assert got.status == "ok"
        assert got.tokens == _serial_tokens(gen, r.prompt,
                                            r.max_new_tokens), r.id


def test_spec_decode_token_parity_and_no_recompile(devices, params):
    """The ISSUE-10 extension of the acceptance pair: with speculative
    decoding armed (n-gram prompt-lookup drafter, fixed-k verify
    program), greedy requests of VARYING prompt lengths — repetitive
    prompts that draft-hit and random ones that mostly miss or fall
    back to plain windows — must (a) emit tokens bit-identical to
    serial Generator calls and (b) grow no jit cache entry (the verify
    included) after the warmup + first admission wave."""
    server = LMServer(params, n_slots=3, window=4, spec_decode=True,
                      draft_k=4, **_kw())
    rng = np.random.default_rng(29)
    reqs = []
    for i in range(8):
        if i % 2:                       # repetitive: the drafter's food
            pat = [int(x) for x in rng.integers(0, VOCAB, 2 + i % 3)]
            prompt = tuple((pat * 6)[:5 + 2 * i])
        else:                           # random: misses and fallbacks
            prompt = tuple(int(x) for x in
                           rng.integers(0, VOCAB, 3 + 2 * i))
        reqs.append(Request(id=f"sp{i}", prompt=prompt,
                            max_new_tokens=4 + (i % 5) * 2))
    server.run([(0.0, r) for r in reqs[:2]])
    sizes = server.engine.cache_sizes()
    assert "verify" in sizes
    server.run([(0.0, r) for r in reqs[2:]])
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    gen = Generator(params, **_kw())
    for r in reqs:
        got = server.poll(r.id)
        assert got is not None and got.status == "ok"
        want = _serial_tokens(gen, r.prompt, r.max_new_tokens)
        assert got.tokens == want, (r.id, got.tokens, want)
    # speculation actually ran (the drafts proposed and verified);
    # correctness above never depended on it
    assert server.summary()["serve_spec_verify_dispatches"] > 0


def test_engine_failure_releases_slots_and_surfaces_error(devices, params):
    """Satellite contract: if the engine fails mid-tick, the in-flight
    requests become status="error" Results (with the failure detail),
    their slots are released, the error re-raises — and the server
    keeps serving new requests afterwards instead of wedging."""
    server = LMServer(params, **_kw(), n_slots=2, window=4, eos_id=None)
    assert server.submit(Request(id="a", prompt=(1, 2, 3),
                                 max_new_tokens=8))
    assert server.submit(Request(id="b", prompt=(4, 5),
                                 max_new_tokens=8))
    server.step()                     # admit a; window in flight
    server.step()                     # admit b; next window in flight
    assert server.scheduler._running

    real_collect = server.engine.collect

    def boom():
        raise RuntimeError("device fell off the bus")

    server.engine.collect = boom
    with pytest.raises(RuntimeError, match="fell off the bus"):
        server.step()
    server.engine.collect = real_collect

    # every in-flight request got an error Result with the detail
    for rid in ("a", "b"):
        r = server.poll(rid)
        assert r is not None and r.status == "error"
        assert "fell off the bus" in r.error
    # slots were released, nothing is running, the queue is sane
    assert server.scheduler._running == {}
    assert sorted(server.engine.free_slots()) == [0, 1]
    assert server.scheduler.idle()

    # the server is still serviceable: a fresh request completes ok and
    # matches the serial path (the engine state machine was not wedged)
    gen = Generator(params, **_kw())
    assert server.submit(Request(id="c", prompt=(1, 2, 3),
                                 max_new_tokens=6))
    out = server.drain()
    assert [r.id for r in out] == ["c"] and out[0].status == "ok"
    assert out[0].error is None
    assert out[0].tokens == _serial_tokens(gen, [1, 2, 3], 6)


def test_chunked_prefill_failure_releases_and_recovers(devices, params):
    """An engine failure raised from a CHUNK dispatch mid-admission
    gets the same cleanup contract as collect/begin_window failures:
    the prefilling entry becomes an error Result, its reserved slot
    frees, and the server keeps serving."""
    server = LMServer(params, n_slots=2, window=4, prefill_chunk=4,
                      **_kw())
    assert server.submit(Request(id="long", prompt=tuple(range(1, 14)),
                                 max_new_tokens=4))
    real_step = server.engine.prefill_step

    def boom(slot):
        raise RuntimeError("chunk dispatch died")

    server.engine.prefill_step = boom
    with pytest.raises(RuntimeError, match="chunk dispatch died"):
        server.step()
    server.engine.prefill_step = real_step

    r = server.poll("long")
    assert r is not None and r.status == "error"
    assert "chunk dispatch died" in r.error
    assert server.scheduler.idle()
    assert sorted(server.engine.free_slots()) == [0, 1]
    # still serviceable, and output still matches serial
    gen = Generator(params, **_kw())
    assert server.submit(Request(id="next", prompt=(1, 2, 3),
                                 max_new_tokens=5))
    server.drain()
    assert server.poll("next").tokens == _serial_tokens(gen, [1, 2, 3],
                                                        5)


def test_engine_failure_preserves_completed_entries(devices, params):
    """A request that COMPLETED on the failed tick (budget reached at
    collect) keeps its real 'ok' Result — only the genuinely in-flight
    request becomes an error — even though tick() re-raised before its
    normal bookkeeping ran."""
    server = LMServer(params, **_kw(), n_slots=2, window=4, eos_id=None)
    assert server.submit(Request(id="done", prompt=(1, 2, 3),
                                 max_new_tokens=4))   # == one window
    assert server.submit(Request(id="run", prompt=(4, 5),
                                 max_new_tokens=12))
    calls = {"n": 0}
    real_begin = server.engine.begin_window

    def failing_begin(n):
        calls["n"] += 1
        if calls["n"] >= 2:          # the window AFTER "done" finishes
            raise RuntimeError("begin blew up")
        return real_begin(n)

    server.engine.begin_window = failing_begin
    server.step()                    # admit both, window 1 in flight
    with pytest.raises(RuntimeError, match="begin blew up"):
        server.step()                # collect: "done" finishes; begin dies
    server.engine.begin_window = real_begin

    done = server.poll("done")
    assert done.status == "ok" and done.finish_reason == "budget"
    assert len(done.tokens) == 4 and done.error is None
    # the serial path agrees with the salvaged tokens
    gen = Generator(params, **_kw())
    assert done.tokens == _serial_tokens(gen, [1, 2, 3], 4)
    failed = server.poll("run")
    assert failed.status == "error" and "begin blew up" in failed.error
    assert len(failed.tokens) == 4   # the collected window's tokens kept
    assert server.scheduler.idle()
