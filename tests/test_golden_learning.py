"""Golden learning-quality tests (VERDICT r2 #4): every training path
must LEARN the synthetic task to a thresholded accuracy in a bounded
budget — not merely "loss went down".

The synthetic task is deliberately learnable (data/synthetic.py: positive
patches carry a brighter center blob), standing in for the real IDC tree
in this no-egress environment; the reference's observable is the same
training-curve evidence (dist_model_tf_vgg.py:67-101).

Thresholds are on TRAIN accuracy for the BN backbones: Keras-parity
BatchNorm momentum is 0.99 (models/core.py batch_norm), so after a
few-epoch budget the eval-mode moving statistics still sit near their
init and val accuracy lags the learned function by design — the same
curve shape the reference's Keras models produce early in training.
All budgets/seeds are deterministic on the virtual CPU mesh, so these
thresholds are pinned measurements, not hopes.
"""

import jax
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import initialize_server, make_fedavg_round
from idc_models_tpu.models import small_cnn
from idc_models_tpu.secure import make_secure_fedavg_round
from idc_models_tpu.train import TwoPhaseConfig, rmsprop, two_phase_fit
from idc_models_tpu.train.losses import binary_cross_entropy

THRESHOLD = 0.9


def _two_phase(name, *, size, n=192, epochs=1, fine_tune_epochs=2,
               lr=1e-3, batch_size=32):
    imgs, labels = synthetic.make_idc_like(n + 64, size=size, seed=3)
    train = ArrayDataset(imgs[:n], labels[:n])
    val = ArrayDataset(imgs[n:], labels[n:])
    return two_phase_fit(name, 1, train, val, meshlib.data_mesh(),
                         TwoPhaseConfig(lr=lr, epochs=epochs,
                                        fine_tune_epochs=fine_tune_epochs,
                                        batch_size=batch_size, seed=0))


def test_vgg16_two_phase_learns_task_from_pretrained(devices, tmp_path):
    """VGG16 two-phase fit reaches >=0.9 fine-tune train accuracy within
    2 + 2 epochs when started from a pretrained backbone — the only way
    the reference ever runs VGG16 (weights='imagenet',
    dist_model_tf_vgg.py:119). No ImageNet artifact exists in this
    environment, so the start is a deterministic signal-preserving
    surrogate (center-tap channel-averaging kernels: each conv passes
    local brightness through, the role ImageNet features play for real
    patches); it flows through the real --pretrained-weights plumbing.
    Probed: 0.932 at the last fine-tune epoch. A random-init VGG16
    cannot learn the blob in this budget (probed at several budgets —
    13 random conv layers + 5 maxpools destroy the brightness signal),
    which is an architecture property, not a machinery gap: Keras
    behaves the same."""
    # environmental gate (ISSUE 7 satellite): on this backend the
    # surrogate's collapsed GAP features make head training oscillate
    # at chance — probed once per session by re-running the mechanism
    # in miniature; the full story lives on the reason string. Runs
    # for real wherever the head descends (the seed backend did).
    import pytest

    from _env_probes import (
        VGG_SURROGATE_SKIP_REASON, vgg_surrogate_head_learns,
    )

    if not vgg_surrogate_head_learns():
        pytest.skip(VGG_SURROGATE_SKIP_REASON)

    from idc_models_tpu.models import pretrained
    from idc_models_tpu.models.vgg import vgg16

    model = vgg16(1)
    shapes = jax.eval_shape(lambda: dict(p=model.init(jax.random.key(0))
                                         .params))["p"]
    bb = {}
    for layer, leaves in shapes["backbone"].items():
        kh, kw, cin, cout = leaves["kernel"].shape
        k = np.zeros((kh, kw, cin, cout), np.float32)
        k[1, 1, :, :] = 1.0 / cin
        bb[layer] = {"kernel": k, "bias": np.zeros((cout,), np.float32)}
    npz = tmp_path / "vgg_surrogate.npz"
    pretrained.save_npz(npz, bb)

    imgs, labels = synthetic.make_idc_like(256, size=50, seed=3)
    train = ArrayDataset(imgs[:192], labels[:192])
    val = ArrayDataset(imgs[192:], labels[192:])
    res = two_phase_fit("vgg16", 1, train, val, meshlib.data_mesh(),
                        TwoPhaseConfig(lr=1e-3, epochs=2,
                                       fine_tune_epochs=2, batch_size=32,
                                       seed=0),
                        pretrained_weights=str(npz))
    assert res.history_fine["accuracy"][-1] >= THRESHOLD, res.history_fine


def test_mobilenet_two_phase_learns_task(devices):
    """MobileNetV2 two-phase fit reaches >=0.9 train accuracy within
    1 + 2 epochs on 192 examples (probed: 0.984 at the last fine-tune
    epoch)."""
    res = _two_phase("mobilenet_v2", size=32)
    assert res.history_fine["accuracy"][-1] >= THRESHOLD, res.history_fine


def test_densenet_two_phase_learns_task(devices):
    """DenseNet201 two-phase fit reaches >=0.9 train accuracy within
    1 + 2 epochs on 192 examples (probed: 1.000)."""
    res = _two_phase("densenet201", size=32)
    assert res.history_fine["accuracy"][-1] >= THRESHOLD, res.history_fine


def test_fedavg_learns_task(devices):
    """40 FedAvg rounds (8 clients, 1 local epoch) reach >=0.9 federated
    train accuracy (probed: crosses 0.9 ~round 30, 0.93-0.96 after)."""
    mesh = meshlib.client_mesh(8)
    model = small_cnn(10, 3, 1)
    imgs, labels = synthetic.make_idc_like(8 * 64, size=10, seed=0)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), 8, iid=True,
                               seed=0)
    w = np.full((8,), 64, np.float32)
    rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                            mesh, local_epochs=1, batch_size=16)
    server = initialize_server(model, jax.random.key(0))
    accs = []
    for r in range(40):
        server, m = rnd(server, ci, cl, w,
                        jax.random.fold_in(jax.random.key(1), r))
        accs.append(float(m["accuracy"]))
    assert max(accs[-10:]) >= THRESHOLD, accs
    assert int(server.round) == 40


def test_secure_fedavg_learns_task(devices):
    """40 masked secure-aggregation rounds reach >=0.9 — the quantized
    masked mean trains as well as the plain one (probed: 0.93-0.94 by
    round 40)."""
    mesh = meshlib.client_mesh(8)
    model = small_cnn(10, 3, 1)
    imgs, labels = synthetic.make_idc_like(8 * 64, size=10, seed=0)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), 8, iid=True,
                               seed=0)
    rnd = make_secure_fedavg_round(model, rmsprop(1e-3),
                                   binary_cross_entropy, mesh, percent=0.5,
                                   local_epochs=1, batch_size=16)
    server = initialize_server(model, jax.random.key(0))
    accs = []
    for r in range(40):
        server, m = rnd(server, ci, cl,
                        jax.random.fold_in(jax.random.key(2), r))
        accs.append(float(m["accuracy"]))
    assert max(accs[-10:]) >= THRESHOLD, accs
