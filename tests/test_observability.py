"""ISSUE 5 observability layer: span tracer export formats, registry
export formats, Timer routing, the offline stats rollup — and the
back-compat gate that every PRE-EXISTING jsonl key/event still emits
unchanged now that the loops also feed the tracer/registry.
"""

import json
import re
import threading

import numpy as np
import pytest

from idc_models_tpu.observe import (
    JsonlLogger, MetricsRegistry, Timer, Tracer, summarize_jsonl, trace,
)


@pytest.fixture()
def tracer():
    tr = Tracer()
    prev = trace.set_tracer(tr)
    yield tr
    trace.set_tracer(prev)


def _nested_work(tracer):
    with trace.span("outer", kind="test"):
        with trace.span("inner.a", i=0):
            pass
        with trace.span("inner.a", i=1):
            with trace.span("leaf"):
                pass
    with trace.span("sibling"):
        pass


# -- tracer ----------------------------------------------------------------


def test_span_ids_and_nesting_roundtrip_jsonl(tracer, tmp_path):
    _nested_work(tracer)
    path = tracer.export_jsonl(tmp_path / "spans.jsonl")
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 5
    ids = [r["id"] for r in recs]
    assert len(set(ids)) == 5                       # process-unique ids
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    outer = by_name["outer"][0]
    assert outer["parent"] is None
    assert outer["attrs"] == {"kind": "test"}
    assert by_name["sibling"][0]["parent"] is None
    for r in by_name["inner.a"]:
        assert r["parent"] == outer["id"]           # nesting via parent
    leaf = by_name["leaf"][0]
    inner1 = [r for r in by_name["inner.a"] if r["attrs"]["i"] == 1][0]
    assert leaf["parent"] == inner1["id"]
    # children fit inside their parent's interval; both clocks present
    for r in recs:
        assert r["dur_ms"] >= 0 and r["t_ms"] >= 0 and r["wall"] > 0
        if r["parent"] is not None:
            p = [x for x in recs if x["id"] == r["parent"]][0]
            assert p["t_ms"] <= r["t_ms"] + 1e-6
            assert (r["t_ms"] + r["dur_ms"]
                    <= p["t_ms"] + p["dur_ms"] + 1e-6)


def test_chrome_trace_export_is_perfetto_valid(tracer, tmp_path):
    """The exported file meets the trace-event format's expectations:
    `ph:"X"` complete events with numeric microsecond ts/dur, pid/tid
    ints, and the same containment the jsonl carries."""
    _nested_work(tracer)
    path = tracer.export_chrome(tmp_path / "trace.json")
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 5
    assert any(e["ph"] == "M" for e in evs)         # process metadata
    by_id = {}
    for e in xs:
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["ts"] >= 0 and e["dur"] >= 0       # microseconds
        by_id[e["args"]["span_id"]] = e
    for e in xs:
        parent = e["args"]["parent_id"]
        if parent is not None:
            p = by_id[parent]
            assert p["ts"] <= e["ts"] + 1e-3
            assert e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1e-3


def test_disabled_tracer_is_noop():
    assert trace.get_tracer() is None
    h1 = trace.span("x", a=1)
    h2 = trace.span("y")
    assert h1 is h2                      # the shared no-op handle
    with h1 as s:
        s.set(b=2)                       # every op accepted, no state


def test_spans_are_per_thread(tracer):
    """Concurrent threads each get their own open-span stack: a span
    opened on thread B must not parent under thread A's open span."""
    ready = threading.Barrier(2)

    def work(tag):
        ready.wait()
        with trace.span(f"t.{tag}"):
            with trace.span(f"t.{tag}.child"):
                pass

    ts = [threading.Thread(target=work, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    recs = tracer.records()
    by_name = {r["name"]: r for r in recs}
    for i in range(2):
        child = by_name[f"t.{i}.child"]
        assert child["parent"] == by_name[f"t.{i}"]["id"]
        assert child["tid"] == by_name[f"t.{i}"]["tid"]


def test_timer_routes_through_tracer(tracer, capsys):
    """Satellite: a legacy Timer shows up in the exported trace while
    its print line stays byte-identical to the reference format."""
    with Timer("Pre-training for 10 epochs") as t:
        pass
    out = capsys.readouterr().out
    assert out == f"Pre-training for 10 epochs took {t.seconds} seconds\n"
    spans = tracer.records()
    assert [s["name"] for s in spans] == ["Pre-training for 10 epochs"]
    assert spans[0]["attrs"] == {"timer": True}


def test_tracing_context_installs_and_exports(tmp_path):
    chrome = tmp_path / "t.json"
    with trace.tracing(chrome_path=chrome) as tr:
        assert trace.get_tracer() is tr
        with trace.span("inside"):
            pass
    assert trace.get_tracer() is None
    assert json.load(open(chrome))["traceEvents"]
    # no paths -> true no-op, nothing installed
    with trace.tracing() as tr2:
        assert tr2 is None and trace.get_tracer() is None


# -- registry --------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests", labels=("status",))
    c.inc(status="ok")
    c.inc(2, status="ok")
    c.inc(status="err")
    assert c.value(status="ok") == 3 and c.value(status="err") == 1
    with pytest.raises(ValueError):
        c.inc(-1, status="ok")           # counters only go up
    with pytest.raises(ValueError):
        c.inc(status="ok", extra="x")    # undeclared label
    g = reg.gauge("depth", "queue depth")
    g.set(4)
    g.dec()
    assert g.value() == 3
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    # idempotent re-registration returns the SAME instrument
    assert reg.counter("reqs_total", labels=("status",)) is c
    # type / label conflicts are loud
    with pytest.raises(ValueError):
        reg.gauge("reqs_total")
    with pytest.raises(ValueError):
        reg.counter("reqs_total", labels=("other",))
    # bucket conflicts are as loud as type/label conflicts — a silent
    # first-wins would file the second caller's observations into +Inf
    with pytest.raises(ValueError):
        reg.histogram("lat_seconds", buckets=(10.0, 20.0))
    assert reg.histogram("lat_seconds", buckets=(0.1, 1.0)) is h
    snap = {(r["name"], tuple(sorted(r["labels"].items()))): r
            for r in reg.snapshot()}
    assert snap[("reqs_total", (("status", "ok"),))]["value"] == 3
    hrec = snap[("lat_seconds", ())]
    assert hrec["count"] == 3 and hrec["min"] == 0.05 and hrec["max"] == 5.0
    assert hrec["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 3}


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.counter("jobs_total", "jobs run", labels=("kind",)).inc(
        3, kind="a b")
    reg.gauge("temp", "gauge").set(1.5)
    h = reg.histogram("dur_seconds", "d", buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(3.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE jobs_total counter" in lines
    assert "# HELP jobs_total jobs run" in lines
    assert 'jobs_total{kind="a b"} 3' in lines
    assert "# TYPE temp gauge" in lines and "temp 1.5" in lines
    assert "# TYPE dur_seconds histogram" in lines
    assert 'dur_seconds_bucket{le="0.5"} 1' in lines
    assert 'dur_seconds_bucket{le="2"} 1' in lines     # cumulative
    assert 'dur_seconds_bucket{le="+Inf"} 2' in lines  # == _count
    assert "dur_seconds_count 2" in lines
    assert any(l.startswith("dur_seconds_sum ") for l in lines)
    # every sample line parses as <name>[{labels}] <number>
    sample = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
                        r"-?[0-9.e+-]+$")
    for l in lines:
        if not l.startswith("#"):
            assert sample.match(l), l
    # non-finite values render as Prometheus's legal spellings instead
    # of crashing the whole exposition on int() overflow
    reg.gauge("hot").set(float("inf"))
    reg.gauge("cold").set(float("-inf"))
    reg.gauge("broken").set(float("nan"))
    text2 = reg.prometheus_text()
    assert "hot +Inf" in text2 and "cold -Inf" in text2
    assert "broken NaN" in text2


def test_registry_jsonl_snapshot_and_stats(tmp_path):
    reg = MetricsRegistry()
    reg.counter("widgets_total").inc(7)
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        logger.log(event="epoch", epoch=0, loss=1.0, accuracy=0.5)
        reg.log_snapshot(logger)
    recs = [json.loads(l) for l in open(log)]
    snaps = [r for r in recs if r["event"] == "metrics_snapshot"]
    assert len(snaps) == 1
    assert snaps[0]["metrics"][0] == {
        "name": "widgets_total", "type": "counter", "labels": {},
        "value": 7}
    # the offline stats rollup reads the same file
    s = summarize_jsonl(log)
    assert s["records"] == 2
    assert s["events"]["epoch"]["fields"]["loss"]["mean"] == 1.0
    assert s["metrics"][0]["name"] == "widgets_total"
    assert reg.write_snapshot(tmp_path / "snap.jsonl")


# -- jsonl back-compat gates ----------------------------------------------
#
# The acceptance bar: every jsonl key/event the pre-ISSUE-5 loops wrote
# still emits with the same names now that the tracer/registry ride
# along. These freeze the schemas at the metrics-hook level (cheap, no
# engine compile); the CLI e2e tests cover the full wiring.


def test_serving_metrics_jsonl_schema_unchanged(tmp_path):
    from idc_models_tpu.serve.metrics import ServingMetrics

    log = tmp_path / "serve.jsonl"
    with JsonlLogger(log) as logger:
        m = ServingMetrics(logger, registry=MetricsRegistry())
        m.on_submit("r0", 10.0)
        m.on_reject("r1", 10.1)
        m.on_admit("r0", 0.02)
        m.on_first_token("r0", 0.05)
        m.on_cycle(queue_depth=1, occupancy=0.5, tokens=3,
                   prefill_s=0.01)
        m.on_finish("r0", n_tokens=3, ttft_s=0.05, decode_s=0.1,
                    reason="budget", t=10.3)
    recs = [json.loads(l) for l in open(log)]
    by_event = {r["event"]: r for r in recs}
    # the historical event set + per-event keys, byte-for-byte names
    assert set(by_event) == {"serve_submit", "serve_reject",
                             "serve_admit", "serve_first_token",
                             "serve_finish"}
    assert set(by_event["serve_submit"]) == {"ts", "event", "id"}
    assert set(by_event["serve_admit"]) == {"ts", "event", "id",
                                            "queue_wait_ms"}
    assert set(by_event["serve_first_token"]) == {
        "ts", "event", "id", "ttft_ms", "prefill_ms"}
    assert set(by_event["serve_finish"]) == {"ts", "event", "id",
                                             "tokens", "reason",
                                             "ttft_ms"}
    # the historical summary keys all still present
    s = m.summary()
    for k in ("serve_requests", "serve_rejected", "serve_timed_out",
              "serve_tokens", "serve_tokens_per_sec",
              "serve_ttft_ms_p50", "serve_ttft_ms_p95",
              "serve_queue_wait_ms_p50", "serve_queue_wait_ms_p95",
              "serve_prefill_ms_p50", "serve_prefill_ms_p95",
              "serve_token_ms_p50", "serve_slot_occupancy",
              "serve_queue_depth_mean", "serve_queue_depth_max",
              "serve_window_tokens_mean",
              "serve_prefill_stall_ms_mean",
              "serve_prefill_stall_ms_max"):
        assert k in s, k


def test_fed_driver_round_health_schema_unchanged(tmp_path):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.federated.driver import DriverConfig, run_rounds
    from idc_models_tpu.federated.fedavg import ServerState

    def round_fn(server, images, labels, weights, rng):
        new = ServerState(round=server.round + 1, params=server.params,
                          model_state=server.model_state)
        return new, {"loss": jnp.float32(0.5),
                     "accuracy": jnp.float32(0.9),
                     "clients_dropped": jnp.int32(0)}

    server = ServerState(round=jnp.zeros((), jnp.int32),
                         params={"w": jnp.ones((2,))}, model_state={})
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        res = run_rounds(round_fn, server, None, None,
                         np.ones(3, np.float32),
                         config=DriverConfig(rounds=2), logger=logger)
    assert len(res.history) == 2
    recs = [json.loads(l) for l in open(log)]
    health = [r for r in recs if r["event"] == "round_health"]
    rounds = [r for r in recs if r["event"] == "round"]
    assert len(health) == 2 and len(rounds) == 2
    assert {"ts", "event", "round", "attempt", "status", "seconds",
            "participants", "loss", "accuracy",
            "clients_dropped"} <= set(health[0])
    assert health[0]["status"] == "ok"
    assert {"round", "attempts", "loss", "accuracy"} <= set(rounds[0])


def test_fit_epoch_jsonl_schema_unchanged(tmp_path, devices):
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import TrainState, fit, rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.random((16, 10, 10, 3)).astype(np.float32),
                      (rng.random(16) > 0.5).astype(np.int32))
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    import jax

    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        fit(model, opt, binary_cross_entropy, state, ds, ds,
            meshlib.data_mesh(), epochs=1, batch_size=8, logger=logger,
            verbose=False)
    recs = [json.loads(l) for l in open(log)]
    eps = [r for r in recs if r["event"] == "epoch"]
    assert len(eps) == 1
    assert set(eps[0]) == {"ts", "event", "epoch", "loss", "accuracy",
                           "val_loss", "val_accuracy"}
