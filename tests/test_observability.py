"""ISSUE 5 observability layer: span tracer export formats, registry
export formats, Timer routing, the offline stats rollup — and the
back-compat gate that every PRE-EXISTING jsonl key/event still emits
unchanged now that the loops also feed the tracer/registry.
"""

import json
import re
import threading

import numpy as np
import pytest

from idc_models_tpu.observe import (
    JsonlLogger, MetricsRegistry, Timer, Tracer, summarize_jsonl, trace,
)


@pytest.fixture()
def tracer():
    tr = Tracer()
    prev = trace.set_tracer(tr)
    yield tr
    trace.set_tracer(prev)


def _nested_work(tracer):
    with trace.span("outer", kind="test"):
        with trace.span("inner.a", i=0):
            pass
        with trace.span("inner.a", i=1):
            with trace.span("leaf"):
                pass
    with trace.span("sibling"):
        pass


# -- tracer ----------------------------------------------------------------


def test_span_ids_and_nesting_roundtrip_jsonl(tracer, tmp_path):
    _nested_work(tracer)
    path = tracer.export_jsonl(tmp_path / "spans.jsonl")
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 5
    ids = [r["id"] for r in recs]
    assert len(set(ids)) == 5                       # process-unique ids
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    outer = by_name["outer"][0]
    assert outer["parent"] is None
    assert outer["attrs"] == {"kind": "test"}
    assert by_name["sibling"][0]["parent"] is None
    for r in by_name["inner.a"]:
        assert r["parent"] == outer["id"]           # nesting via parent
    leaf = by_name["leaf"][0]
    inner1 = [r for r in by_name["inner.a"] if r["attrs"]["i"] == 1][0]
    assert leaf["parent"] == inner1["id"]
    # children fit inside their parent's interval; both clocks present
    for r in recs:
        assert r["dur_ms"] >= 0 and r["t_ms"] >= 0 and r["wall"] > 0
        if r["parent"] is not None:
            p = [x for x in recs if x["id"] == r["parent"]][0]
            assert p["t_ms"] <= r["t_ms"] + 1e-6
            assert (r["t_ms"] + r["dur_ms"]
                    <= p["t_ms"] + p["dur_ms"] + 1e-6)


def test_chrome_trace_export_is_perfetto_valid(tracer, tmp_path):
    """The exported file meets the trace-event format's expectations:
    `ph:"X"` complete events with numeric microsecond ts/dur, pid/tid
    ints, and the same containment the jsonl carries."""
    _nested_work(tracer)
    path = tracer.export_chrome(tmp_path / "trace.json")
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 5
    assert any(e["ph"] == "M" for e in evs)         # process metadata
    by_id = {}
    for e in xs:
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["ts"] >= 0 and e["dur"] >= 0       # microseconds
        by_id[e["args"]["span_id"]] = e
    for e in xs:
        parent = e["args"]["parent_id"]
        if parent is not None:
            p = by_id[parent]
            assert p["ts"] <= e["ts"] + 1e-3
            assert e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1e-3


def test_disabled_tracer_is_noop():
    assert trace.get_tracer() is None
    h1 = trace.span("x", a=1)
    h2 = trace.span("y")
    assert h1 is h2                      # the shared no-op handle
    with h1 as s:
        s.set(b=2)                       # every op accepted, no state


def test_spans_are_per_thread(tracer):
    """Concurrent threads each get their own open-span stack: a span
    opened on thread B must not parent under thread A's open span."""
    ready = threading.Barrier(2)

    def work(tag):
        ready.wait()
        with trace.span(f"t.{tag}"):
            with trace.span(f"t.{tag}.child"):
                pass

    ts = [threading.Thread(target=work, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    recs = tracer.records()
    by_name = {r["name"]: r for r in recs}
    for i in range(2):
        child = by_name[f"t.{i}.child"]
        assert child["parent"] == by_name[f"t.{i}"]["id"]
        assert child["tid"] == by_name[f"t.{i}"]["tid"]


def test_timer_routes_through_tracer(tracer, capsys):
    """Satellite: a legacy Timer shows up in the exported trace while
    its print line stays byte-identical to the reference format."""
    with Timer("Pre-training for 10 epochs") as t:
        pass
    out = capsys.readouterr().out
    assert out == f"Pre-training for 10 epochs took {t.seconds} seconds\n"
    spans = tracer.records()
    assert [s["name"] for s in spans] == ["Pre-training for 10 epochs"]
    assert spans[0]["attrs"] == {"timer": True}


def test_detached_spans_explicit_parenting_and_close(tracer):
    """ISSUE-7 request-lifecycle primitives: a detached span never
    touches the thread's open-span stack, parents explicitly, closes
    idempotently with late attrs, and `point` records a marker."""
    with trace.span("tick"):
        req = trace.start_span("request", rid="r0")
        child = trace.start_span("queued", parent=req.span_id, rid="r0")
        # stack parenting is unaffected: a normal span opened while the
        # detached ones are live still parents under "tick"
        with trace.span("inner") as inner:
            pass
        inner.close(bogus=True)   # stray close on a stack span: no-op
        child.close(queue_wait_ms=1.5)
        child.close(queue_wait_ms=999.0)       # second close: no-op
        trace.point("first_token", parent=req.span_id, rid="r0")
        req.close(status="ok")
    recs = {r["name"]: r for r in tracer.records()}
    assert recs["request"]["parent"] is None
    assert recs["queued"]["parent"] == recs["request"]["id"]
    assert recs["queued"]["attrs"]["queue_wait_ms"] == 1.5
    assert recs["first_token"]["parent"] == recs["request"]["id"]
    assert recs["inner"]["parent"] == recs["tick"]["id"]
    assert "bogus" not in recs["inner"]["attrs"]
    assert recs["request"]["attrs"]["status"] == "ok"
    # exactly one record per span despite the double close
    assert len(tracer.records()) == 5


def test_detached_spans_disabled_are_the_noop_handle():
    assert trace.get_tracer() is None
    h = trace.start_span("request", rid="r0")
    assert h is trace.point("x") is trace.span("y")
    # the chained-call-site contract: the no-op handle's span_id is the
    # "no parent" value, so rid chains need no enabled/disabled branch
    assert h.span_id is None
    h.close(status="ok")                       # accepted, no state


def test_tracing_context_installs_and_exports(tmp_path):
    chrome = tmp_path / "t.json"
    with trace.tracing(chrome_path=chrome) as tr:
        assert trace.get_tracer() is tr
        with trace.span("inside"):
            pass
    assert trace.get_tracer() is None
    assert json.load(open(chrome))["traceEvents"]
    # no paths -> true no-op, nothing installed
    with trace.tracing() as tr2:
        assert tr2 is None and trace.get_tracer() is None


# -- registry --------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests", labels=("status",))
    c.inc(status="ok")
    c.inc(2, status="ok")
    c.inc(status="err")
    assert c.value(status="ok") == 3 and c.value(status="err") == 1
    with pytest.raises(ValueError):
        c.inc(-1, status="ok")           # counters only go up
    with pytest.raises(ValueError):
        c.inc(status="ok", extra="x")    # undeclared label
    g = reg.gauge("depth", "queue depth")
    g.set(4)
    g.dec()
    assert g.value() == 3
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    # idempotent re-registration returns the SAME instrument
    assert reg.counter("reqs_total", labels=("status",)) is c
    # type / label conflicts are loud
    with pytest.raises(ValueError):
        reg.gauge("reqs_total")
    with pytest.raises(ValueError):
        reg.counter("reqs_total", labels=("other",))
    # bucket conflicts are as loud as type/label conflicts — a silent
    # first-wins would file the second caller's observations into +Inf
    with pytest.raises(ValueError):
        reg.histogram("lat_seconds", buckets=(10.0, 20.0))
    assert reg.histogram("lat_seconds", buckets=(0.1, 1.0)) is h
    snap = {(r["name"], tuple(sorted(r["labels"].items()))): r
            for r in reg.snapshot()}
    assert snap[("reqs_total", (("status", "ok"),))]["value"] == 3
    hrec = snap[("lat_seconds", ())]
    assert hrec["count"] == 3 and hrec["min"] == 0.05 and hrec["max"] == 5.0
    assert hrec["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 3}


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.counter("jobs_total", "jobs run", labels=("kind",)).inc(
        3, kind="a b")
    reg.gauge("temp", "gauge").set(1.5)
    h = reg.histogram("dur_seconds", "d", buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(3.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE jobs_total counter" in lines
    assert "# HELP jobs_total jobs run" in lines
    assert 'jobs_total{kind="a b"} 3' in lines
    assert "# TYPE temp gauge" in lines and "temp 1.5" in lines
    assert "# TYPE dur_seconds histogram" in lines
    assert 'dur_seconds_bucket{le="0.5"} 1' in lines
    assert 'dur_seconds_bucket{le="2"} 1' in lines     # cumulative
    assert 'dur_seconds_bucket{le="+Inf"} 2' in lines  # == _count
    assert "dur_seconds_count 2" in lines
    assert any(l.startswith("dur_seconds_sum ") for l in lines)
    # every sample line parses as <name>[{labels}] <number>
    sample = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
                        r"-?[0-9.e+-]+$")
    for l in lines:
        if not l.startswith("#"):
            assert sample.match(l), l
    # non-finite values render as Prometheus's legal spellings instead
    # of crashing the whole exposition on int() overflow
    reg.gauge("hot").set(float("inf"))
    reg.gauge("cold").set(float("-inf"))
    reg.gauge("broken").set(float("nan"))
    text2 = reg.prometheus_text()
    assert "hot +Inf" in text2 and "cold -Inf" in text2
    assert "broken NaN" in text2


def test_registry_jsonl_snapshot_and_stats(tmp_path):
    reg = MetricsRegistry()
    reg.counter("widgets_total").inc(7)
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        logger.log(event="epoch", epoch=0, loss=1.0, accuracy=0.5)
        reg.log_snapshot(logger)
    recs = [json.loads(l) for l in open(log)]
    snaps = [r for r in recs if r["event"] == "metrics_snapshot"]
    assert len(snaps) == 1
    assert snaps[0]["metrics"][0] == {
        "name": "widgets_total", "type": "counter", "labels": {},
        "value": 7}
    # the offline stats rollup reads the same file
    s = summarize_jsonl(log)
    assert s["records"] == 2
    assert s["events"]["epoch"]["fields"]["loss"]["mean"] == 1.0
    assert s["metrics"][0]["name"] == "widgets_total"
    assert reg.write_snapshot(tmp_path / "snap.jsonl")


# -- jsonl back-compat gates ----------------------------------------------
#
# The acceptance bar: every jsonl key/event the pre-ISSUE-5 loops wrote
# still emits with the same names now that the tracer/registry ride
# along. These freeze the schemas at the metrics-hook level (cheap, no
# engine compile); the CLI e2e tests cover the full wiring.


def test_serving_metrics_jsonl_schema_unchanged(tmp_path):
    from idc_models_tpu.serve.metrics import ServingMetrics

    log = tmp_path / "serve.jsonl"
    reg = MetricsRegistry()
    with JsonlLogger(log) as logger:
        m = ServingMetrics(logger, registry=reg)
        m.on_submit("r0", 10.0)
        m.on_reject("r1", 10.1)
        m.on_admit("r0", 0.02)
        m.on_first_token("r0", 0.05)
        m.on_cycle(queue_depth=1, occupancy=0.5, tokens=3,
                   prefill_s=0.01)
        m.on_finish("r0", n_tokens=3, ttft_s=0.05, decode_s=0.1,
                    reason="budget", t=10.3)
        # ISSUE-8 resilience hooks: NEW event types only — the
        # historical five keep their exact shapes below
        m.on_slot_fault("r2", kind="nonfinite_logits", slot=1)
        m.on_retry("r2", attempt=2, delay_s=0.05)
        m.on_shed("r3")
        m.on_clamp("r4", asked=64, clamp=8)
        m.on_fault_injected("stall", tick=3)
        # ISSUE-10 speculative hooks: one NEW event type, frozen from
        # day one; dispatch counting logs nothing
        m.on_dispatch("window")
        m.on_dispatch("verify")
        m.on_spec(drafted=8, accepted=5, emitted=7, slots=2)
        # ISSUE-11 paged-KV hooks: one NEW event type (exhaustion),
        # frozen from day one; on_pages sets gauges + peaks, no event
        m.on_pages(pages_total=32, pages_used=10, pages_cached=3,
                   resident_tokens=150, resident_bytes=40960)
        m.on_pages(pages_total=32, pages_used=7, pages_cached=3,
                   resident_tokens=90, resident_bytes=28672)
        m.on_page_exhausted(rid="r9", needed=48)
    recs = [json.loads(l) for l in open(log)]
    by_event = {r["event"]: r for r in recs}
    # the historical event set + per-event keys, byte-for-byte names
    assert set(by_event) == {"serve_submit", "serve_reject",
                             "serve_admit", "serve_first_token",
                             "serve_finish", "serve_slot_fault",
                             "serve_retry", "serve_shed",
                             "serve_clamp", "serve_fault_injected",
                             "serve_spec_verify",
                             "serve_page_exhausted"}
    assert set(by_event["serve_submit"]) == {"ts", "event", "id"}
    assert set(by_event["serve_admit"]) == {"ts", "event", "id",
                                            "queue_wait_ms"}
    assert set(by_event["serve_first_token"]) == {
        "ts", "event", "id", "ttft_ms", "prefill_ms"}
    assert set(by_event["serve_finish"]) == {"ts", "event", "id",
                                             "tokens", "reason",
                                             "ttft_ms"}
    # the ISSUE-8 events are frozen from day one, same discipline
    assert set(by_event["serve_slot_fault"]) == {"ts", "event", "id",
                                                 "kind", "slot"}
    assert set(by_event["serve_retry"]) == {"ts", "event", "id",
                                            "attempt", "delay_ms"}
    assert set(by_event["serve_shed"]) == {"ts", "event", "id"}
    assert set(by_event["serve_clamp"]) == {"ts", "event", "id",
                                            "max_new_tokens", "asked"}
    assert set(by_event["serve_fault_injected"]) == {"ts", "event",
                                                     "kind", "tick"}
    # the ISSUE-10 speculative event, frozen from day one
    assert set(by_event["serve_spec_verify"]) == {"ts", "event",
                                                  "drafted", "accepted",
                                                  "emitted", "slots"}
    # the ISSUE-11 paged-KV event, frozen from day one
    assert set(by_event["serve_page_exhausted"]) == {"ts", "event",
                                                     "id", "needed"}
    # the historical summary keys all still present
    s = m.summary()
    for k in ("serve_requests", "serve_rejected", "serve_timed_out",
              "serve_tokens", "serve_tokens_per_sec",
              "serve_ttft_ms_p50", "serve_ttft_ms_p95",
              "serve_queue_wait_ms_p50", "serve_queue_wait_ms_p95",
              "serve_prefill_ms_p50", "serve_prefill_ms_p95",
              # ISSUE-20 additive ITL tail next to the existing p50
              "serve_token_ms_p50", "serve_token_ms_p95",
              "serve_slot_occupancy",
              "serve_queue_depth_mean", "serve_queue_depth_max",
              "serve_window_tokens_mean",
              "serve_prefill_stall_ms_mean",
              "serve_prefill_stall_ms_max",
              # the ISSUE-8 additive resilience rollup
              "serve_slot_faults", "serve_retries", "serve_shed",
              "serve_clamped", "serve_faults_injected",
              # the ISSUE-10 additive speculative rollup (incl. the
              # SHARED tokens-per-dispatch definition both modes use)
              "serve_decode_dispatches", "serve_tokens_per_dispatch",
              "serve_spec_verify_dispatches", "serve_spec_drafted",
              "serve_spec_accepted", "serve_spec_accept_rate",
              "serve_spec_tokens_per_dispatch",
              # the ISSUE-11 additive paged-KV rollup, frozen from
              # day one
              "serve_kv_pages_total", "serve_kv_pages_used_peak",
              "serve_kv_resident_tokens_peak",
              "serve_kv_resident_bytes_peak",
              "serve_kv_tokens_per_hbm_byte",
              "serve_page_exhaustions"):
        assert k in s, k
    assert s["serve_slot_faults"] == 1 and s["serve_retries"] == 1
    assert s["serve_shed"] == 1 and s["serve_clamped"] == 1
    assert s["serve_decode_dispatches"] == 2
    assert s["serve_tokens_per_dispatch"] == 1.5   # 3 tokens / 2
    assert s["serve_spec_accept_rate"] == 0.625    # 5 / 8 drafted
    assert s["serve_spec_tokens_per_dispatch"] == 3.5  # 7 / 2 slots
    # paged rollup keeps PEAKS (the capacity claim is stated at peak
    # residency), and tokens-per-byte is taken AT the peak
    assert s["serve_kv_pages_total"] == 32
    assert s["serve_kv_pages_used_peak"] == 10
    assert s["serve_kv_resident_tokens_peak"] == 150
    assert s["serve_kv_resident_bytes_peak"] == 40960
    assert s["serve_kv_tokens_per_hbm_byte"] == round(150 / 40960, 6)
    assert s["serve_page_exhaustions"] == 1
    # ISSUE-20: inter-token latency rides next to TTFT — a histogram
    # on the registry (the fleet view merges its state) and a p95
    # summary tail. One finish, 3 tokens over 0.1s decode: the mean
    # ITL is 0.1 / 2 = 50ms.
    assert s["serve_token_ms_p95"] == 50.0
    itl = reg.get("serve_itl_seconds")
    assert itl is not None and itl.kind == "histogram"
    (_, st), = itl._series()
    assert st["count"] == 1 and abs(st["sum"] - 0.05) < 1e-9


def test_fed_driver_round_health_schema_unchanged(tmp_path):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.federated.driver import DriverConfig, run_rounds
    from idc_models_tpu.federated.fedavg import ServerState

    def round_fn(server, images, labels, weights, rng):
        new = ServerState(round=server.round + 1, params=server.params,
                          model_state=server.model_state)
        return new, {"loss": jnp.float32(0.5),
                     "accuracy": jnp.float32(0.9),
                     "clients_dropped": jnp.int32(0)}

    server = ServerState(round=jnp.zeros((), jnp.int32),
                         params={"w": jnp.ones((2,))}, model_state={})
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        res = run_rounds(round_fn, server, None, None,
                         np.ones(3, np.float32),
                         config=DriverConfig(rounds=2), logger=logger)
    assert len(res.history) == 2
    recs = [json.loads(l) for l in open(log)]
    health = [r for r in recs if r["event"] == "round_health"]
    rounds = [r for r in recs if r["event"] == "round"]
    assert len(health) == 2 and len(rounds) == 2
    assert {"ts", "event", "round", "attempt", "status", "seconds",
            "participants", "loss", "accuracy",
            "clients_dropped"} <= set(health[0])
    assert health[0]["status"] == "ok"
    assert {"round", "attempts", "loss", "accuracy"} <= set(rounds[0])


def test_fed_cohort_jsonl_schema_frozen(tmp_path):
    """ISSUE-13 satellite: the NEW `fed_cohort` event's key sets (sync
    and async shapes) are frozen from day one; the historical fed
    events (`round`, `round_health`) stay byte-identical — gated by
    test_fed_driver_round_health_schema_unchanged above and re-checked
    here against a population-mode run log."""
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.federated import (
        ClientPopulation, CohortSampler, initialize_server,
        make_async_round, make_population_round,
    )
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    pop = ClientPopulation(32, examples_per_client=8, image_size=10,
                           seed=0)
    model = small_cnn(10, 3, 1)
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        sync = make_population_round(
            model, rmsprop(1e-3), binary_cross_entropy,
            meshlib.client_mesh(1), pop, CohortSampler(pop, 4, seed=1),
            wave_size=2, local_epochs=1, batch_size=8, logger=logger)
        srv = initialize_server(model, jax.random.key(0))
        sync(srv, None, None, None, jax.random.key(1), round_idx=0)
        a = make_async_round(
            model, rmsprop(1e-3), binary_cross_entropy, pop,
            CohortSampler(pop, 4, seed=1), buffer_size=2,
            local_epochs=1, batch_size=8, seed=2, logger=logger)
        srv = initialize_server(model, jax.random.key(0))
        a(srv, None, None, None, None, round_idx=0)
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    cohorts = [r for r in recs if r["event"] == "fed_cohort"]
    assert len(cohorts) == 2
    sync_rec = next(r for r in cohorts if r["mode"] == "sync")
    async_rec = next(r for r in cohorts if r["mode"] == "async")
    # FROZEN key sets — extending is a new event, not a reshaped one
    assert set(sync_rec) == {"ts", "event", "round", "mode",
                             "population", "cohort", "participants",
                             "waves", "wave_size"}
    assert set(async_rec) == {"ts", "event", "round", "mode",
                              "population", "cohort", "participants",
                              "buffer", "updates", "staleness_mean",
                              "staleness_max", "staleness_hist"}
    assert async_rec["staleness_hist"] == list(async_rec[
        "staleness_hist"])
    assert len(async_rec["staleness_hist"]) == 6
    assert sum(async_rec["staleness_hist"]) == \
        async_rec["participants"]


def test_stats_fed_cohorts_section(tmp_path):
    """`stats` renders the per-round cohort/buffer/staleness story from
    fed_cohort events — the ISSUE-13 'fed cohorts' section."""
    from idc_models_tpu.observe.stats import format_summary

    log = tmp_path / "run.jsonl"
    recs = [
        {"ts": 1.0, "event": "fed_cohort", "round": 0, "mode": "sync",
         "population": 10000, "cohort": 256, "participants": 256,
         "waves": 8, "wave_size": 32},
        {"ts": 2.0, "event": "fed_cohort", "round": 1, "mode": "async",
         "population": 10000, "cohort": 256, "participants": 256,
         "buffer": 8, "updates": 32, "staleness_mean": 1.25,
         "staleness_max": 4,
         "staleness_hist": [100, 80, 40, 20, 10, 6]},
    ]
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    s = summarize_jsonl(log)
    assert len(s["fed_cohorts"]) == 2
    assert s["fed_cohorts"][0]["mode"] == "sync"
    assert s["fed_cohorts"][1]["staleness_hist"] == \
        [100, 80, 40, 20, 10, 6]
    text = format_summary(s)
    assert "fed cohorts (per round)" in text
    assert "cohort=256 of 10000" in text
    assert "waves=8x32" in text
    assert "buffer=8 updates=32" in text
    assert "[100, 80, 40, 20, 10, 6]" in text


def test_stats_request_timeline_from_events_and_spans(tmp_path):
    """ISSUE-7 satellite: `summarize_jsonl` groups serve_* events AND
    rid-stamped span records into per-request timelines; the --request
    renderer orders them and a missing rid is loud."""
    from idc_models_tpu.observe import format_request_timeline

    log = tmp_path / "mixed.jsonl"
    recs = [
        {"ts": 100.0, "event": "serve_submit", "id": "r0"},
        {"ts": 100.1, "event": "serve_admit", "id": "r0",
         "queue_wait_ms": 100.0},
        {"event": "span", "name": "serve.prefill_chunk", "id": 7,
         "parent": 3, "tid": 1, "t_ms": 150.0, "dur_ms": 30.0,
         "wall": 100.15, "attrs": {"rid": "r0", "slot": 1}},
        {"ts": 100.3, "event": "serve_first_token", "id": "r0",
         "ttft_ms": 300.0, "prefill_ms": 200.0},
        {"ts": 100.5, "event": "serve_finish", "id": "r0", "tokens": 4,
         "reason": "budget", "ttft_ms": 300.0},
        {"ts": 100.2, "event": "serve_submit", "id": "r1"},
        # rid-less span: belongs to no request
        {"event": "span", "name": "serve.tick", "id": 9, "parent": None,
         "tid": 1, "t_ms": 0.0, "dur_ms": 1.0, "wall": 100.0,
         "attrs": {}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    s = summarize_jsonl(log)
    assert set(s["requests"]) == {"r0", "r1"}
    r0 = s["requests"]["r0"]
    assert [e["what"] for e in r0] == [
        "serve_submit", "serve_admit", "serve.prefill_chunk",
        "serve_first_token", "serve_finish"]
    assert r0[0]["t_s"] == 0.0
    assert r0[2]["dur_ms"] == 30.0 and r0[2]["detail"]["slot"] == 1
    assert r0[4]["t_s"] == pytest.approx(0.5)
    text = format_request_timeline(s, "r0")
    assert "request r0" in text and "serve.prefill_chunk" in text
    assert "serve_finish" in text and "reason=budget" in text
    with pytest.raises(KeyError):
        format_request_timeline(s, "nope")


def test_stats_covers_train_and_fed_jsonl(tmp_path):
    """ISSUE-7 satellite: the stats rollup over train/fed-SHAPED run
    logs (epoch + round + round_health + timer records), not just the
    serve path — field percentiles, timer table, and no spurious
    request table."""
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        for e in range(3):
            logger.log(event="epoch", epoch=e, loss=1.0 - 0.2 * e,
                       accuracy=0.5 + 0.1 * e, val_loss=1.1 - 0.2 * e,
                       val_accuracy=0.45 + 0.1 * e)
        for r in range(4):
            logger.log(event="round", round=r, train_loss=0.9 - 0.1 * r,
                       train_acc=0.6 + 0.05 * r, test_loss=1.0,
                       test_acc=0.55)
            logger.log(event="round_health", round=r, attempt=0,
                       status="ok", seconds=0.05, participants=8,
                       loss=0.9 - 0.1 * r)
        logger.log(event="timer", name="Federated training",
                   seconds=1.25)
    s = summarize_jsonl(log)
    assert s["events"]["epoch"]["count"] == 3
    assert s["events"]["epoch"]["fields"]["loss"]["min"] == 0.6
    assert s["events"]["round"]["count"] == 4
    assert s["events"]["round"]["fields"]["train_loss"]["max"] == 0.9
    assert s["events"]["round_health"]["fields"]["seconds"]["mean"] \
        == 0.05
    assert s["timers"]["Federated training"]["count"] == 1
    assert s["requests"] == {}        # nothing serve-shaped in the log


def test_bench_compare_flags_directional_regressions(tmp_path):
    """ISSUE-7 satellite: bench_compare diffs the two newest
    BENCH_rNN.json records, honoring each key's good direction and the
    10% tolerance; under two files is loud."""
    import sys as _sys
    from pathlib import Path as _Path

    _sys.path.insert(0, str(_Path(__file__).parent.parent))
    try:
        import bench
    finally:
        _sys.path.pop(0)

    def rec(**kw):
        return {"metric": "x", **kw}

    old = rec(value=100.0, serve_ttft_ms_p95=100.0, fed_round_s=1.0,
              mfu=0.6)
    # throughput -20% (regression), ttft +50% (regression), round -30%
    # (improvement), mfu +5% (inside tolerance)
    new = rec(value=80.0, serve_ttft_ms_p95=150.0, fed_round_s=0.7,
              mfu=0.63)
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(old))
    # the driver-record shape (bench line inside `tail`) parses too
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "rc": 0, "tail": "noise\n" + json.dumps(new) + "\n"}))
    out = bench.bench_compare(tmp_path)
    assert out["new"].endswith("BENCH_r02.json")
    assert set(out["regressions"]) == {"value", "serve_ttft_ms_p95"}
    assert out["keys"]["fed_round_s"]["regressed"] is False
    assert out["keys"]["mfu"]["regressed"] is False
    assert out["keys"]["value"]["ratio"] == pytest.approx(0.8)
    with pytest.raises(ValueError):
        bench.bench_compare(tmp_path / "empty")
    # every documented headline key really is documented
    docs = (_Path(__file__).parent.parent / "docs"
            / "BENCHMARKS.md").read_text()
    for key in bench.HIGHER_IS_BETTER + bench.LOWER_IS_BETTER:
        assert f"`{key}`" in docs, (
            f"bench_compare headline key {key!r} missing from "
            f"docs/BENCHMARKS.md")


def test_bench_keys_all_classified_directional_or_neutral():
    """ISSUE-20 satellite: every constant key a bench_* function returns
    must be classified — either in a direction table (and therefore
    documented, via the gate above) or in bench.NEUTRAL_KEYS with a
    rationale.  A new bench metric that lands unclassified fails here
    instead of silently dropping out of bench_compare; a NEUTRAL_KEYS
    entry whose bench went away fails the stale check."""
    import ast
    import sys as _sys
    from pathlib import Path as _Path

    repo = _Path(__file__).parent.parent
    _sys.path.insert(0, str(repo))
    try:
        import bench
    finally:
        _sys.path.pop(0)

    tree = ast.parse((repo / "bench.py").read_text())
    emitted = set()
    for fn in ast.walk(tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name.startswith("bench_")):
            continue
        # dict literals assigned to a local that is later returned count
        # the same as a literal `return {...}`
        assigned: dict[str, ast.Dict] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Dict)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assigned[node.targets[0].id] = node.value
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return):
                continue
            val = node.value
            if isinstance(val, ast.Name):
                val = assigned.get(val.id)
            if not isinstance(val, ast.Dict):
                continue
            for key in val.keys:
                if isinstance(key, ast.Constant) and isinstance(
                        key.value, str):
                    emitted.add(key.value)
    assert len(emitted) > 100, "bench.py key scan came back implausibly thin"

    directional = set(bench.HIGHER_IS_BETTER) | set(bench.LOWER_IS_BETTER)
    neutral = set(bench.NEUTRAL_KEYS)
    assert not (directional & neutral), sorted(directional & neutral)
    unclassified = emitted - directional - neutral
    assert not unclassified, (
        f"bench keys missing a direction (add to HIGHER_IS_BETTER / "
        f"LOWER_IS_BETTER + docs, or to NEUTRAL_KEYS): "
        f"{sorted(unclassified)}")
    stale = neutral - emitted
    assert not stale, (
        f"NEUTRAL_KEYS entries no bench emits any more: {sorted(stale)}")


def test_profile_program_jsonl_schema_frozen(tmp_path, devices):
    """ISSUE-9: the `profile_program` event's key set is frozen from
    day one (NEW event; the ten historical event schemas are gated
    above/by their own tests). The record is built through the ONE
    construction site (profile.program_record) the CLI uses."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.observe import profile as prof

    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    cost = prof.program_report(compiled, name="sch.prog")
    roofline = prof.roofline_verdict(
        cost, 0.001, spec=prof.RooflineSpec("x", 100.0, 1000.0))
    log = tmp_path / "profile.jsonl"
    with JsonlLogger(log) as logger:
        logger.log(event="profile_program",
                   **prof.program_record(cost, roofline, step_ms=1.0,
                                         device_kind="cpu"))
    rec = json.loads(log.read_text().splitlines()[0])
    assert set(rec) == {
        "ts", "event", "program", "flops", "bytes_accessed",
        "arithmetic_intensity", "argument_bytes", "output_bytes",
        "temp_bytes", "peak_hbm_bytes", "generated_code_bytes",
        "available", "step_ms", "verdict", "achieved_tflops",
        "achieved_hbm_gbps", "mfu", "hbm_utilization",
        "bound_fraction", "ridge_intensity", "peak_tflops",
        "peak_hbm_gbps", "device_kind"}
    assert rec["event"] == "profile_program"
    assert rec["program"] == "sch.prog" and rec["available"] is True
    assert rec["verdict"] in ("compute-bound", "bandwidth-bound")
    # a verdict-less (unknown-backend) record keeps the SAME keys
    with JsonlLogger(log) as logger:
        logger.log(event="profile_program",
                   **prof.program_record(cost))
    rec2 = json.loads(log.read_text().splitlines()[-1])
    assert set(rec2) == set(rec)
    assert rec2["verdict"] == "unknown" and rec2["mfu"] is None


def test_profile_step_jsonl_schema_frozen(tmp_path):
    """ISSUE-9: the `profile_step` event's key set is frozen, built
    through profile.step_record from a real DeviceTimeline report."""
    from idc_models_tpu.observe import MetricsRegistry
    from idc_models_tpu.observe import profile as prof

    records = [
        {"event": "span", "name": "profile.step", "id": 1,
         "parent": None, "tid": 1, "t_ms": 0.0, "dur_ms": 10.0,
         "wall": 0.0, "attrs": {}},
        {"event": "span", "name": "device.sync", "id": 2, "parent": 1,
         "tid": 1, "t_ms": 1.0, "dur_ms": 6.0, "wall": 0.0,
         "attrs": {}},
    ]
    tl = prof.DeviceTimeline(registry=MetricsRegistry()).consume(records)
    log = tmp_path / "profile.jsonl"
    with JsonlLogger(log) as logger:
        for loop, st in tl.report().items():
            logger.log(event="profile_step",
                       **prof.step_record(loop, st))
    rec = json.loads(log.read_text().splitlines()[0])
    assert set(rec) == {"ts", "event", "loop", "steps", "wall_ms",
                        "device_ms", "host_gap_ms",
                        "device_busy_fraction", "host_gap_fraction",
                        "step_ms_mean"}
    assert rec["loop"] == "profile.step"
    assert rec["device_busy_fraction"] == pytest.approx(0.6)
    assert (rec["device_busy_fraction"] + rec["host_gap_fraction"]
            == pytest.approx(1.0))


def test_stats_span_self_time_table(tmp_path):
    """ISSUE-9 satellite: per-span-name EXCLUSIVE time from any span
    export — parent self-time excludes direct children; --top bounds
    the rendered table."""
    from idc_models_tpu.observe import format_summary

    recs = [
        {"event": "span", "name": "tick", "id": 1, "parent": None,
         "tid": 1, "t_ms": 0.0, "dur_ms": 10.0, "wall": 1.0,
         "attrs": {}},
        {"event": "span", "name": "collect", "id": 2, "parent": 1,
         "tid": 1, "t_ms": 1.0, "dur_ms": 4.0, "wall": 1.0,
         "attrs": {}},
        {"event": "span", "name": "window", "id": 3, "parent": 1,
         "tid": 1, "t_ms": 6.0, "dur_ms": 3.0, "wall": 1.0,
         "attrs": {}},
        {"event": "span", "name": "tick", "id": 4, "parent": None,
         "tid": 1, "t_ms": 11.0, "dur_ms": 5.0, "wall": 1.0,
         "attrs": {}},
    ]
    log = tmp_path / "spans.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    s = summarize_jsonl(log)
    self_t = s["span_self"]
    # tick inclusive 15, children 7 -> self 8; leaves keep their dur
    assert self_t["tick"]["count"] == 2
    assert self_t["tick"]["total_ms"] == 15.0
    assert self_t["tick"]["self_ms"] == 8.0
    assert self_t["collect"]["self_ms"] == 4.0
    assert self_t["window"]["self_ms"] == 3.0
    assert self_t["tick"]["self_pct"] == pytest.approx(
        100.0 * 8 / 15, abs=0.01)
    text = format_summary(s, top=1)
    assert "span self-time (exclusive, top 1 of 3):" in text
    assert "tick" in text.split("span self-time")[1]
    # negative-self clamping: a child longer than its parent
    recs2 = [
        {"event": "span", "name": "p", "id": 1, "parent": None,
         "tid": 1, "t_ms": 0.0, "dur_ms": 2.0, "wall": 1.0,
         "attrs": {}},
        {"event": "span", "name": "c", "id": 2, "parent": 1, "tid": 1,
         "t_ms": 0.0, "dur_ms": 3.0, "wall": 1.0, "attrs": {}},
    ]
    log2 = tmp_path / "spans2.jsonl"
    log2.write_text("\n".join(json.dumps(r) for r in recs2) + "\n")
    assert summarize_jsonl(log2)["span_self"]["p"]["self_ms"] == 0.0
    # append-mode logs hold MULTIPLE runs whose span ids restart per
    # process — a repeated id starts a new segment, so run 2's children
    # must not subtract from run 1's same-id parents
    two_runs = recs + recs          # same ids twice = two runs appended
    log3 = tmp_path / "spans3.jsonl"
    log3.write_text("\n".join(json.dumps(r) for r in two_runs) + "\n")
    st = summarize_jsonl(log3)["span_self"]
    assert st["tick"]["count"] == 4
    assert st["tick"]["self_ms"] == 16.0      # 2x the single-run 8.0
    assert st["collect"]["self_ms"] == 8.0


def test_fit_epoch_jsonl_schema_unchanged(tmp_path, devices):
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.models import small_cnn
    from idc_models_tpu.train import TrainState, fit, rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.random((16, 10, 10, 3)).astype(np.float32),
                      (rng.random(16) > 0.5).astype(np.int32))
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    import jax

    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        fit(model, opt, binary_cross_entropy, state, ds, ds,
            meshlib.data_mesh(), epochs=1, batch_size=8, logger=logger,
            verbose=False)
    recs = [json.loads(l) for l in open(log)]
    eps = [r for r in recs if r["event"] == "epoch"]
    assert len(eps) == 1
    assert set(eps[0]) == {"ts", "event", "epoch", "loss", "accuracy",
                           "val_loss", "val_accuracy"}


def test_tenant_jsonl_schemas_frozen_from_day_one(tmp_path):
    """ISSUE-14: the four tenant-labeled event shapes — finish, shed,
    quota rejection, per-tenant brownout transition — are frozen from
    day one, and every HISTORICAL serve event stays byte-untouched
    (the hooks above prove that; here the tenant twins prove theirs).
    The summary grows ONE additive key: serve_tenants, one record per
    registered tenant with zeros included."""
    from idc_models_tpu.observe.stats import format_summary
    from idc_models_tpu.serve.metrics import ServingMetrics
    from idc_models_tpu.serve.tenancy import TenantQuota, TenantRegistry

    reg = TenantRegistry()
    reg.register("acme", quota=TenantQuota(max_queued=4),
                 slo_ttft_p95_ms=200.0)
    reg.register("globex")
    log = tmp_path / "serve.jsonl"
    with JsonlLogger(log) as logger:
        mreg = MetricsRegistry()
        ten = reg.build(logger=logger, registry=mreg,
                        brownout_dwell_s=0.0)
        m = ServingMetrics(logger, registry=mreg, tenancy=ten)
        m.on_submit("r0", 10.0, tenant="acme")
        m.on_first_token("r0", 0.05, tenant="acme")
        m.on_finish("r0", n_tokens=3, ttft_s=0.05, decode_s=0.1,
                    reason="budget", t=10.3, tenant="acme")
        m.on_shed("r1", tenant="acme")
        m.on_tenant_quota("r2", tenant="acme", kind="queued")
        m.on_tenant_cycle(["acme", "globex"], depths={"acme": 2},
                          slots={"acme": 1}, pages={})
        ten.brownouts["acme"].force_stage(1, reason="drill")
    recs = [json.loads(l) for l in open(log)]
    by_event = {r["event"]: r for r in recs}
    # tenant events are NEW types; the historical serve_* shapes they
    # ride next to keep their exact frozen key sets
    assert set(by_event["serve_submit"]) == {"ts", "event", "id"}
    assert set(by_event["serve_finish"]) == {"ts", "event", "id",
                                             "tokens", "reason",
                                             "ttft_ms"}
    assert set(by_event["serve_shed"]) == {"ts", "event", "id"}
    # the ISSUE-14 tenant events, frozen from day one
    assert set(by_event["serve_tenant_finish"]) == {
        "ts", "event", "id", "tenant", "tokens", "reason", "ttft_ms"}
    assert set(by_event["serve_tenant_shed"]) == {"ts", "event", "id",
                                                  "tenant"}
    assert set(by_event["serve_tenant_quota_reject"]) == {
        "ts", "event", "id", "tenant", "kind"}
    assert set(by_event["serve_tenant_brownout"]) == {
        "ts", "event", "tenant", "stage", "stage_name", "direction",
        "reason"}
    # the additive summary key: one record per REGISTERED tenant,
    # zeros included (globex untouched reads as explicit zeros)
    s = m.summary()
    assert set(s["serve_tenants"]) == {"acme", "globex"}
    assert s["serve_tenants"]["acme"] == {
        "requests": 1, "tokens": 3, "ttft_ms_p50": 50.0,
        "ttft_ms_p95": 50.0, "shed": 1, "quota_rejections": 1,
        "slo_breached": False}
    assert s["serve_tenants"]["globex"]["requests"] == 0
    # the offline stats rollup reads the tenant events into its own
    # per-tenant table
    st = summarize_jsonl(log)
    assert st["tenants"]["acme"]["requests"] == 1
    assert st["tenants"]["acme"]["shed"] == 1
    assert st["tenants"]["acme"]["quota_rejections"] == 1
    assert st["tenants"]["acme"]["by_reason"] == {"budget": 1}
    rendered = format_summary(st)
    assert "tenants:" in rendered and "acme" in rendered


def test_checkpoint_rollout_jsonl_schemas_frozen(tmp_path, devices):
    """ISSUE-17: the three new event shapes — ckpt_save, ckpt_restore,
    serve_rollout — are frozen from day one; the summary grows three
    additive keys (serve_rollouts / serve_rollout_outcome /
    serve_rollout_stage) and the offline stats rollup reads the events
    into its checkpoints/rollouts sections."""
    from idc_models_tpu.checkpoint import restore_sharded, save_sharded
    from idc_models_tpu.observe.stats import format_summary
    from idc_models_tpu.serve.metrics import ServingMetrics

    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        mreg = MetricsRegistry()
        save_sharded(tmp_path / "ck",
                     {"w": np.arange(8, dtype=np.float32)}, step=2,
                     logger=logger, registry=mreg)
        restore_sharded(tmp_path / "ck", logger=logger, registry=mreg)
        m = ServingMetrics(logger, registry=mreg)
        m.on_rollout(stage="staging")
        m.on_rollout(stage="canary")
        m.on_rollout(stage="promoted", outcome="promoted",
                     canary_requests=5)
    recs = [json.loads(l) for l in open(log)]
    by_event = {r["event"]: r for r in recs}
    # the ISSUE-17 events, frozen from day one
    assert set(by_event["ckpt_save"]) == {
        "ts", "event", "path", "step", "leaves", "shards", "bytes",
        "seconds", "background"}
    assert set(by_event["ckpt_restore"]) == {
        "ts", "event", "path", "leaves", "shards_read", "bytes_read",
        "peak_host_bytes", "seconds", "sharded"}
    assert set(by_event["serve_rollout"]) == {
        "ts", "event", "stage", "outcome", "canary_requests", "reason"}
    # the additive summary keys: rollout count, terminal outcome, the
    # stage the machine ended in
    s = m.summary()
    assert s["serve_rollouts"] == 1
    assert s["serve_rollout_outcome"] == "promoted"
    assert s["serve_rollout_stage"] == "promoted"
    # registry instruments from day one
    names = {rec["name"] for rec in mreg.snapshot()}
    assert {"ckpt_saves_total", "ckpt_restores_total",
            "ckpt_bytes_written_total", "ckpt_bytes_read_total",
            "serve_rollouts_total", "serve_rollout_stage_code"} <= names
    # the offline stats rollup: transfer totals + the transition list
    st = summarize_jsonl(log)
    assert st["checkpoints"]["saves"] == 1
    assert st["checkpoints"]["restores"] == 1
    assert st["checkpoints"]["save_bytes"] == 32
    assert st["checkpoints"]["restore_bytes"] == 32
    assert st["checkpoints"]["restore_peak_host_bytes"] > 0
    assert [r["stage"] for r in st["rollouts"]] == [
        "staging", "canary", "promoted"]
    assert st["rollouts"][-1]["outcome"] == "promoted"
    rendered = format_summary(st)
    assert "checkpoints:" in rendered and "rollouts" in rendered


# -- ISSUE 20: every emitted event name is pinned or allowlisted ------------


def test_prefix_and_compile_cache_event_schemas_frozen(tmp_path):
    """The remaining serve-side cache events, frozen from their first
    pinning: prefix hit/miss/evict, the cluster-registry adoption
    marker, and the compile-cache epilogue snapshot (whose payload IS
    `CompileCache.summary()` — one source of truth for both)."""
    from idc_models_tpu.serve.compile_cache import CompileCache
    from idc_models_tpu.serve.metrics import ServingMetrics
    from idc_models_tpu.serve.prefix_cache import PrefixCache
    from idc_models_tpu.serve.cluster import PrefixRegistry

    log = tmp_path / "cache.jsonl"
    chunk = 4
    snap = lambda: {"k": np.zeros((chunk, 4), np.float32)}
    logits = np.zeros(4, np.float32)
    shared = PrefixRegistry(chunk, 1 << 20)
    with JsonlLogger(log) as logger:
        # a sibling cache publishes a prefix into the cluster registry
        feeder = PrefixCache(chunk, 1 << 20, shared=shared)
        assert feeder.insert(np.arange(chunk), snap(), logits)
        # budget fits ONE snapshot: the second insert LRU-evicts
        one = PrefixCache(chunk, 96, logger=logger,
                          registry=MetricsRegistry())
        assert one.insert(np.arange(chunk), snap(), logits)
        one.lookup(np.arange(2 * chunk))               # hit
        assert one.insert(np.arange(chunk) + 1, snap(), logits)
        one.lookup(np.arange(chunk) + 3)               # miss
        # an EMPTY local cache adopts the registry's longer prefix
        adopter = PrefixCache(chunk, 1 << 20, logger=logger,
                              registry=MetricsRegistry(),
                              shared=shared)
        n, caches, _ = adopter.lookup(np.arange(2 * chunk))
        assert n == chunk and caches is not None
        cc = CompileCache(tmp_path / "cc")
        m = ServingMetrics(logger, registry=MetricsRegistry())
        m.on_compile_cache(cc)
    recs = [json.loads(l) for l in open(log)]
    by_event = {}
    for r in recs:
        by_event.setdefault(r["event"], set()).add(frozenset(r))
    assert by_event["serve_prefix_hit"] == {frozenset(
        {"ts", "event", "prefix_tokens", "prompt_tokens"})}
    assert by_event["serve_prefix_miss"] == {frozenset(
        {"ts", "event", "prompt_tokens"})}
    assert by_event["serve_prefix_evict"] == {frozenset(
        {"ts", "event", "freed_bytes"})}
    assert by_event["serve_prefix_shared_hit"] == {frozenset(
        {"ts", "event", "prefix_tokens", "prompt_tokens"})}
    assert by_event["serve_compile_cache"] == {frozenset(
        {"ts", "event"} | set(cc.summary()))}


# one contract line per jsonl event name the package can emit — either
# "pin:" the test that freezes its schema, or "allow:" WHY no frozen
# per-event schema applies. The scan below fails on any event emitted
# but missing here (new events must be pinned or documented before
# they ship) AND on any entry no longer emitted (stale contracts rot).
EVENT_CONTRACTS = {
    # serving metrics events (serve/metrics.py)
    **dict.fromkeys(
        ["serve_submit", "serve_reject", "serve_admit",
         "serve_first_token", "serve_finish", "serve_slot_fault",
         "serve_retry", "serve_shed", "serve_clamp",
         "serve_fault_injected", "serve_spec_verify",
         "serve_page_exhausted"],
        "pin:test_serving_metrics_jsonl_schema_unchanged"),
    **dict.fromkeys(
        ["serve_tenant_finish", "serve_tenant_quota_reject",
         "serve_tenant_shed"],
        "pin:test_tenant_jsonl_schemas_frozen_from_day_one"),
    **dict.fromkeys(
        ["serve_rollout", "ckpt_save", "ckpt_restore"],
        "pin:test_checkpoint_rollout_jsonl_schemas_frozen"),
    **dict.fromkeys(
        ["serve_prefix_hit", "serve_prefix_miss", "serve_prefix_evict",
         "serve_prefix_shared_hit", "serve_compile_cache"],
        "pin:test_prefix_and_compile_cache_event_schemas_frozen"),
    "profile_program": "pin:test_profile_program_jsonl_schema_frozen",
    "profile_step": "pin:test_profile_step_jsonl_schema_frozen",
    "fed_cohort": "pin:test_fed_cohort_jsonl_schema_frozen",
    "round_health": "pin:test_fed_driver_round_health_schema_unchanged",
    "epoch": "pin:test_fit_epoch_jsonl_schema_unchanged",
    "metrics_snapshot": "pin:test_registry_jsonl_snapshot_and_stats",
    # cluster trace-hop + fleet events (ISSUE 20)
    **dict.fromkeys(
        ["cluster_place", "cluster_handoff", "cluster_slot_migrate",
         "cluster_scale_up", "cluster_drain", "cluster_prefix_publish",
         "autoscale_decision"],
        "pin:test_fleet_observability.py::"
        "test_autoscaled_migration_renders_one_merged_timeline"),
    **dict.fromkeys(
        ["cluster_canary", "cluster_shed", "cluster_rollout"],
        "pin:test_fleet_observability.py::"
        "test_canary_and_shed_events_carry_the_trace_schema"),
    "cluster_anomaly": (
        "pin:test_fleet_observability.py::"
        "test_watchdog_detectors_fire_once_and_stay_silent_when_clean"),
    **dict.fromkeys(
        ["cluster_migrate", "cluster_replica_dead"],
        "pin:test_cluster.py::"
        "test_failover_keeps_trace_id_in_merged_timeline"),
    "cluster_hedge": (
        "pin:test_cluster.py::"
        "test_hedge_first_result_wins_and_survives_owner_death"),
    # journal WAL records (serve/journal.py)
    **dict.fromkeys(
        ["journal_submit", "journal_finish"],
        "pin:test_cluster.py::"
        "test_kill_drill_migrates_journal_bit_identical"),
    "journal_migrate": "pin:test_elastic.py (drain/migration drills)",
    "journal_progress": ("pin:test_serve_resilience.py (journal "
                         "replay drills)"),
    "compile_cache": "pin:test_elastic.py (warm spin-up drills)",
    "slo_alert": "pin:test_slo.py",
    "slo_resolved": "pin:test_slo.py",
    # dynamic-payload records: their keys are METRIC sets, not fixed
    # schemas — the corresponding summary-key tests freeze the keys
    "serve_summary": ("allow: payload is LMServer.summary() — keys "
                      "frozen by the summary-key assertions in "
                      "test_serving_metrics_jsonl_schema_unchanged"),
    "cluster_summary": ("allow: payload is Router.summary() — the "
                        "cluster rollup keys, asserted in "
                        "test_cluster.py"),
    "step": "allow: training-loop record; metric keys are preset-defined",
    "round": "allow: fed-loop record; metric keys are preset-defined",
    "val": "allow: eval-loop record; metric keys are preset-defined",
    "test": "allow: eval-loop record; metric keys are preset-defined",
    "generate": "allow: sampling demo record (cli), free-form text",
    "timer": ("allow: {name, seconds} utility record — behavior "
              "covered by test_timer_routes_through_tracer"),
}


def _emitted_event_names():
    """AST scan: every constant ``event=`` kwarg passed to a ``.log``
    or ``._log`` call anywhere in the package."""
    import ast
    from pathlib import Path

    import idc_models_tpu

    root = Path(idc_models_tpu.__file__).parent
    names = set()
    for p in sorted(root.rglob("*.py")):
        tree = ast.parse(p.read_text(), filename=str(p))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            attr = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if attr not in ("log", "_log"):
                continue
            for kw in node.keywords:
                if (kw.arg == "event"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    names.add(kw.value.value)
    return names


def test_every_emitted_event_name_is_pinned_or_allowlisted():
    """The frozen-jsonl discipline, enforced structurally: a NEW event
    name cannot ship without either a schema-pinning test or a
    documented allowlist reason, and a contract for an event that no
    longer exists fails loudly instead of rotting."""
    emitted = _emitted_event_names()
    assert emitted, "the scan found no events — scanner broken?"
    unpinned = emitted - set(EVENT_CONTRACTS)
    assert not unpinned, (
        f"events emitted without a schema pin or allowlist entry: "
        f"{sorted(unpinned)} — add a frozen-schema test (preferred) "
        f"or a documented allow: entry to EVENT_CONTRACTS")
    stale = set(EVENT_CONTRACTS) - emitted
    assert not stale, (
        f"EVENT_CONTRACTS entries no longer emitted anywhere: "
        f"{sorted(stale)} — delete them (or the event was renamed "
        f"without updating its pin)")
    for name, contract in EVENT_CONTRACTS.items():
        assert contract.startswith(("pin:", "allow:")), (name, contract)
